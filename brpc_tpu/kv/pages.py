"""KV-cache pages — first-class transferable objects with an explicit
RDMA-style lifecycle.

A serving session's KV-cache is not a blob to serialize: it is a set of
**pages** (one per layer cache array) that a prefill tier *exports*,
*describes* over the control plane, and a decode tier *imports* — the
payload itself moving as registered memory (the in-process/ICI fabric,
or a shm ring slot), never through the serialized message path.  This
module is the export registry: the sender-side bookkeeping that makes a
page a capability with a bounded lifetime instead of a leaked alias.

Lifecycle (mirrors ``transport/shm_ring``'s slot discipline):

    export    the page's device array is posted on the ICI fabric
              (``InProcessFabric.post`` — the "memory registration")
              and pinned in a FIXED page table under a fresh
              generation; the table is bounded, so a leak is visible
              as exhaustion, not as silent growth
    describe  ``(page_id, generation, nbytes)`` — 12 bytes on the wire
              per page; the generation makes every descriptor
              single-lifetime (a recycled page id cannot resolve an
              old descriptor)
    import    one-shot: resolves the descriptor through the registry
              and CONSUMES the fabric entry (``InProcessFabric.take``),
              so a second import of the same descriptor — or an import
              after the exporter released — fails LOUDLY with
              :class:`KvPageError` (surfaced as ERESPONSE by the
              handoff service, never "success with an empty cache")
    release   generation-checked: releasing a page twice, or with a
              stale generation, raises instead of freeing the table
              slot's NEXT tenant

Pages are tagged with an **owner** key at export (the client
connection whose session they belong to): a dying socket sweeps its
pages (``on_socket_closed``, wired into ``Socket.release`` next to the
shm sweep), and the drain plane waits for every outstanding exported
page to settle before the process exits (``drain_settle``, bounded by
the drain grace like the shm ring's).

Since the paged-KV round this module is the **allocator**, not just the
courier.  Three more planes live here:

- :class:`PageAllocator` — host-side bookkeeping for the continuous
  batcher's device page pool (block-paged attention,
  ``models/transformer_lm.make_paged_batch_decode``): a fixed pool of
  fixed-size token pages, REFCOUNTED so the prefix cache can alias a
  session's immutable full pages, generation-checked so a stale alias
  fails loudly instead of reading the slot's next tenant;
- :class:`PrefixCache` — a radix tree over page-granular token-chunk
  fingerprints: a re-sent system prompt / chat history hits, ALIASES
  the shared pages (refcount up, zero bytes moved — the round-18
  import-is-an-alias discipline applied inside one pool) and skips
  prefill for the covered prefix;
- :class:`HostPagePool` — the LRU eviction tier: a cold session's
  private pages spill to a pinned host-RAM pool under the shm ring's
  slot discipline (fixed slots, one memcpy per page, generation-checked
  handles, loud double-free) and re-import on resume.  Mid-spill pages
  are an in-flight gauge the drain plane counts (``drain_settle``): at
  grace expiry the pool is marked aborted and its owner closes the
  parked sessions under the named ``kv_spill_drain_aborted`` reason.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG

define_flag("kv_pages", 256,
            "size of the KV page export table (exported-but-unsettled "
            "pages; bounded so leaks surface as exhaustion)",
            validator=lambda v: isinstance(v, int) and 0 < v <= 65535)

# ---------------------------------------------------------------------------
# Closed reason/event enums (no "unknown" bucket — tools/check/enums.py
# requires a test pin per member, the same discipline as transport.py's
# KV_FALLBACK_REASONS).
# ---------------------------------------------------------------------------

# stream close reasons the ALLOCATOR can emit: every session the paged
# batcher refuses or abandons closes under exactly one of these
KV_EVICT_REASONS = (
    "kv_pool_exhausted",       # no device pages free for a new session
    "kv_host_tier_full",       # spill refused: the host tier is full too
    "kv_spill_drain_aborted",  # drain grace expired on a mid-evict spill
)

# prefix-cache outcome events (counters, closed set)
PREFIX_CACHE_EVENTS = (
    "prefix_hit",              # every full page of the context aliased
    "prefix_partial_hit",      # a proper prefix aliased, remainder
    #                            caught up by teacher-forced steps
    "prefix_miss",             # nothing aliased: full bucketed prefill
    "prefix_insert",           # a new prefix entered the radix tree
    "prefix_evict",            # an LRU entry released its page refs
)

_evict_lock = threading.Lock()
_evicts: Dict[str, int] = {r: 0 for r in KV_EVICT_REASONS}
_prefix_events: Dict[str, int] = {e: 0 for e in PREFIX_CACHE_EVENTS}


def count_evict(reason: str) -> None:
    assert reason in _evicts, f"unnamed kv evict reason {reason!r}"
    with _evict_lock:
        _evicts[reason] += 1
    try:
        from .. import fleet
        fleet.record_event("fleet_kv_evict", reason)
    except Exception:
        pass


def count_prefix(event: str) -> None:
    assert event in _prefix_events, f"unnamed prefix event {event!r}"
    with _evict_lock:
        _prefix_events[event] += 1


def kv_evict_counters() -> Dict[str, int]:
    with _evict_lock:
        return dict(_evicts)


def prefix_event_counters() -> Dict[str, int]:
    with _evict_lock:
        return dict(_prefix_events)

_DESC_FMT = "<IIQ"          # page_id, generation, nbytes
DESC_BYTES = struct.calcsize(_DESC_FMT)


class KvPageError(Exception):
    """A KV page descriptor this process cannot honor — stale
    generation, double import, double free, or an unknown page.  A
    protocol violation, not a fallback shape: the handoff service
    answers ERESPONSE (the import side must fail loudly, never hand
    the decoder an empty cache)."""


class KvPageHandle:
    """Sender-side lease of one exported page (settle exactly once)."""

    __slots__ = ("page_id", "gen", "nbytes")

    def __init__(self, page_id: int, gen: int, nbytes: int):
        self.page_id = page_id
        self.gen = gen
        self.nbytes = nbytes

    def describe(self) -> bytes:
        return struct.pack(_DESC_FMT, self.page_id, self.gen,
                           self.nbytes)


def decode_desc(data: bytes) -> Tuple[int, int, int]:
    if len(data) != DESC_BYTES:
        raise KvPageError(f"malformed kv page descriptor "
                          f"({len(data)} bytes)")
    return struct.unpack(_DESC_FMT, data)


class _Rec:
    __slots__ = ("desc_id", "nbytes", "owner", "imported")

    def __init__(self, desc_id: int, nbytes: int, owner: Any):
        self.desc_id = desc_id
        self.nbytes = nbytes
        self.owner = owner
        self.imported = False


class KvPageStore:
    """The process's page export table (fixed size, generation-checked
    — the shm ring's slot model applied to device arrays)."""

    def __init__(self, npages: int):
        self.npages = int(npages)
        self._lock = threading.Lock()
        self._recs: List[Optional[_Rec]] = [None] * self.npages
        self._gen = [0] * self.npages
        self._free = list(range(self.npages))
        self.exported = 0            # lifetime counters (stats)
        self.imported = 0
        self.swept = 0

    # -- export ------------------------------------------------------------

    def export_array(self, array: Any, nbytes: int,
                     owner: Any = None) -> Optional[KvPageHandle]:
        """Register one page (a live device array) for transfer.  The
        array is posted on the in-process fabric — kept alive and
        addressable until imported, released, or swept.  Returns None
        when the table is full (the caller falls back under a NAMED
        reason — exhaustion is backpressure, not an error)."""
        from ..ici.fabric import in_process_fabric
        with self._lock:
            if not self._free:
                return None
            page_id = self._free.pop()
            self._gen[page_id] += 1
            gen = self._gen[page_id]
        desc_id = in_process_fabric().post(array, nbytes)
        with self._lock:
            self._recs[page_id] = _Rec(desc_id, nbytes, owner)
            self.exported += 1
        return KvPageHandle(page_id, gen, nbytes)

    # -- import (one-shot, loud) -------------------------------------------

    def import_page(self, page_id: int, gen: int, nbytes: int) -> Any:
        """Resolve a descriptor into its array, CONSUMING the fabric
        entry: the importer owns the array from here on.  Stale
        generation, unknown page, size mismatch, or a second import all
        raise :class:`KvPageError` — the loud-failure contract."""
        from ..ici.fabric import in_process_fabric
        with self._lock:
            rec = self._recs[page_id] \
                if 0 <= page_id < self.npages else None
            if rec is None or self._gen[page_id] != gen:
                raise KvPageError(
                    f"stale kv page import (page {page_id} gen {gen})")
            if rec.imported:
                raise KvPageError(
                    f"kv page {page_id} already imported")
            if rec.nbytes != nbytes:
                raise KvPageError(
                    f"kv page {page_id} size mismatch "
                    f"({nbytes} != {rec.nbytes})")
            desc_id = rec.desc_id
            rec.imported = True
        arr = in_process_fabric().take(desc_id)
        if arr is None:
            # released/swept between the rec check and the take — the
            # registry says live but the registration is gone: loud
            raise KvPageError(
                f"kv page {page_id} no longer registered")
        with self._lock:
            self.imported += 1
        return arr

    # -- release (generation-checked, loud on misuse) ----------------------

    def release(self, page_id: int, gen: int) -> None:
        """Settle one exported page (the sender's end-of-handoff).
        Double-free and stale-generation frees raise — a silent no-op
        here would free the table slot's NEXT tenant one day."""
        from ..ici.fabric import in_process_fabric
        with self._lock:
            rec = self._recs[page_id] \
                if 0 <= page_id < self.npages else None
            if rec is None or self._gen[page_id] != gen:
                raise KvPageError(
                    f"double/stale kv page free (page {page_id} "
                    f"gen {gen})")
            self._recs[page_id] = None
            self._free.append(page_id)
            desc_id, imported = rec.desc_id, rec.imported
        if not imported:
            # never imported: drop the fabric registration ourselves
            in_process_fabric().release(desc_id)

    def settle_handles(self, handles) -> None:
        """Release a handoff's whole page set (each exactly once)."""
        for h in handles:
            self.release(h.page_id, h.gen)

    # -- sweeps / drain ----------------------------------------------------

    def release_owner(self, owner: Any) -> int:
        """Reclaim every page tagged with ``owner`` (its connection
        died before the handoff settled).  Soft by design — the sweep
        races legitimate settles and must not throw at either."""
        from ..ici.fabric import in_process_fabric
        stale = []
        with self._lock:
            for page_id, rec in enumerate(self._recs):
                if rec is not None and rec.owner == owner:
                    self._recs[page_id] = None
                    self._free.append(page_id)
                    if not rec.imported:
                        stale.append(rec.desc_id)
                    self.swept += 1
        for desc_id in stale:
            in_process_fabric().release(desc_id)
        return len(stale)

    def outstanding(self) -> int:
        with self._lock:
            return self.npages - len(self._free)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pages": self.npages,
                    "outstanding": self.npages - len(self._free),
                    "exported": self.exported,
                    "imported": self.imported,
                    "swept": self.swept}


# ---------------------------------------------------------------------------
# Process-wide registry (mirrors shm_ring's process_tx_ring shape)
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_store: Optional[KvPageStore] = None


def process_kv_store() -> KvPageStore:
    global _store
    with _reg_lock:
        if _store is None:
            _store = KvPageStore(int(get_flag("kv_pages")))
        return _store


def on_socket_closed(owner: Any) -> None:
    """Sweep pages exported for a dead connection (its handoff will
    never settle) — wired into ``Socket.release`` next to the shm
    sweep, so it runs on the owning loop and must stay non-blocking."""
    with _reg_lock:
        store = _store
    if store is not None:
        n = store.release_owner(owner)
        if n:
            LOG.info("kv page sweep: %d page(s) of dead owner %r", n,
                     owner)


def outstanding_pages() -> int:
    """Exported-but-unsettled pages — the drain plane's gauge (0 when
    the kv plane never engaged)."""
    with _reg_lock:
        store = _store
    return store.outstanding() if store is not None else 0


def drain_settle(deadline_mono_s: float) -> int:
    """Operability plane: wait — bounded by the drain-grace deadline —
    for every outstanding exported page to settle (handoff responses
    release them; dead-conn sweeps run from socket close) AND for every
    host-tier spill in flight to land or abort.  At deadline expiry any
    pool still mid-spill is marked aborted so its owner force-closes
    the parked sessions under the named ``kv_spill_drain_aborted``
    reason — a page mid-evict at drain time settles or closes loudly,
    it never leaks.  Returns pages + spills still outstanding at the
    deadline (0 = fully settled)."""
    import time as _time
    ev = threading.Event()
    while True:
        n = outstanding_pages() + host_inflight_spills()
        if n == 0:
            return 0
        if _time.monotonic() >= deadline_mono_s:
            for pool in list(_host_pools):
                if pool.inflight():
                    pool.drain_abort("kv_spill_drain_aborted")
            return n
        ev.wait(0.005)     # timed: the drain path stays deadline-bound


def _reset_for_tests() -> None:
    global _store
    with _reg_lock:
        _store = None
    with _evict_lock:
        for k in _evicts:
            _evicts[k] = 0
        for k in _prefix_events:
            _prefix_events[k] = 0


# ===========================================================================
# The allocator planes (paged-KV round).  Everything below is HOST-side
# bookkeeping: the device page pool itself lives in the batcher's cache
# pytree (``models/transformer_lm.empty_paged_cache``); these classes
# decide which rows of it a session may touch.
# ===========================================================================


class PageAllocator:
    """Refcounted free-list over the device page pool's row blocks.

    Page 0 is RESERVED as the garbage page: unallocated block-table
    entries and inactive-slot writes land there, and the attention mask
    never admits it — so the allocator only ever hands out pages
    ``1..num_pages-1``.

    Refcounts exist for the prefix cache: a session's immutable full
    pages are aliased (``ref``) by the radix tree and by later sessions
    that hit it; the page returns to the free list only when the LAST
    holder releases.  Each return bumps the page's generation, so a
    stale alias (a bug, by construction) fails loudly on the next
    generation check instead of reading the row's next tenant.
    """

    def __init__(self, num_pages: int, page_tokens: int,
                 page_bytes: int = 0):
        if num_pages < 2:
            raise ValueError("PageAllocator needs >= 2 pages "
                             "(page 0 is the reserved garbage page)")
        self.num_pages = int(num_pages)
        self.page_tokens = int(page_tokens)
        self.page_bytes = int(page_bytes)   # device bytes per page (stats)
        self._lock = threading.Lock()
        self._ref = [0] * self.num_pages
        self._gen = [0] * self.num_pages
        # LIFO free list, page 0 never enters it
        self._free = list(range(self.num_pages - 1, 0, -1))
        self.peak_in_use = 0
        self.alloc_failures = 0

    # -- allocation --------------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` fresh pages (refcount 1 each).  Returns None when
        the pool cannot cover the request — exhaustion is backpressure
        with a NAMED close reason (``kv_pool_exhausted``), never a
        partial grant."""
        with self._lock:
            if n > len(self._free):
                self.alloc_failures += 1
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._ref[p] = 1
            self._note_peak_locked()
            return pages

    def ref(self, page_id: int) -> None:
        """Alias a live page (prefix-cache hit / radix insert).  Only a
        page somebody already holds can be aliased — ref'ing a free
        page would resurrect a row the pool may re-grant."""
        with self._lock:
            if not (0 < page_id < self.num_pages) \
                    or self._ref[page_id] <= 0:
                raise KvPageError(
                    f"alias of dead kv device page {page_id}")
            self._ref[page_id] += 1

    def release(self, page_id: int) -> None:
        """Drop one hold.  The page rejoins the free list (generation
        bumped) when the last holder releases.  Double-release raises —
        a silent no-op would free an aliased page under a live
        session."""
        with self._lock:
            if not (0 < page_id < self.num_pages) \
                    or self._ref[page_id] <= 0:
                raise KvPageError(
                    f"double/stale kv device page free (page "
                    f"{page_id})")
            self._ref[page_id] -= 1
            if self._ref[page_id] == 0:
                self._gen[page_id] += 1
                self._free.append(page_id)

    def release_all(self, pages) -> None:
        for p in pages:
            self.release(p)

    # -- generation / stats ------------------------------------------------

    def gen_of(self, page_id: int) -> int:
        with self._lock:
            return self._gen[page_id]

    def refcount(self, page_id: int) -> int:
        with self._lock:
            return self._ref[page_id]

    def in_use(self) -> int:
        with self._lock:
            return self.num_pages - 1 - len(self._free)

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def _note_peak_locked(self) -> None:
        used = self.num_pages - 1 - len(self._free)
        if used > self.peak_in_use:
            self.peak_in_use = used

    def stats(self) -> Dict[str, int]:
        with self._lock:
            used = self.num_pages - 1 - len(self._free)
            return {"pages": self.num_pages,
                    "page_tokens": self.page_tokens,
                    "in_use": used,
                    "free": len(self._free),
                    "peak_in_use": self.peak_in_use,
                    "alloc_failures": self.alloc_failures,
                    "bytes_in_use": used * self.page_bytes}


class _PrefixNode:
    __slots__ = ("digest", "page", "gen", "children", "parent", "tick")

    def __init__(self, digest: bytes, page: int, gen: int,
                 parent: Optional["_PrefixNode"], tick: int):
        self.digest = digest
        self.page = page
        self.gen = gen
        self.children: Dict[bytes, "_PrefixNode"] = {}
        self.parent = parent
        self.tick = tick


class PrefixCache:
    """Radix tree over page-granular token-chunk fingerprints.

    Granularity is FULL pages only: a page is cached only once the
    session that wrote it can never write it again (its context's full
    pages — decode writes land at positions >= ctx_len), so aliasing
    needs no copy-on-write and a hit moves ZERO bytes.  The partial
    tail of a context is never shared; a hit's remainder is caught up
    with teacher-forced decode steps, which keeps token identity with
    the uncached path exact by construction.

    Each node fingerprints one page-sized token chunk (chained blake2b,
    so a digest commits to the whole prefix, not just its own chunk),
    holds ONE page id plus the allocator's generation snapshot, and
    takes its own refcount on the page — a cached page cannot return to
    the free list, which is what makes the generation check an
    invariant assertion rather than a race guard.  Eviction is
    leaf-first LRU (a parent is never younger than a live child), so
    the tree stays a valid prefix set under any budget.
    """

    def __init__(self, alloc: PageAllocator,
                 budget_pages: Optional[int] = None):
        self._alloc = alloc
        self._page = alloc.page_tokens
        self._budget = budget_pages
        self._lock = threading.Lock()
        self._root: Dict[bytes, _PrefixNode] = {}
        self._nodes = 0
        self._tick = 0
        self.hits = 0
        self.partial_hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0

    # -- fingerprints ------------------------------------------------------

    def _digests(self, tokens) -> List[bytes]:
        """Chained per-page digests of the FULL pages of ``tokens``."""
        n_full = len(tokens) // self._page
        out: List[bytes] = []
        prev = b""
        for i in range(n_full):
            chunk = tokens[i * self._page:(i + 1) * self._page]
            payload = struct.pack(f"<{self._page}q",
                                  *(int(t) for t in chunk))
            prev = hashlib.blake2b(prev + payload,
                                   digest_size=16).digest()
            out.append(prev)
        return out

    # -- lookup (takes refs) -----------------------------------------------

    def lookup(self, ctx_tokens) -> Tuple[List[int], int]:
        """Longest cached prefix of ``ctx_tokens``.  Returns
        ``(pages, covered_tokens)`` with one reference TAKEN per page —
        the caller owns those holds and must release them with the rest
        of the session's block table.  Counts exactly one of
        prefix_hit / prefix_partial_hit / prefix_miss."""
        digs = self._digests(ctx_tokens)
        with self._lock:
            self._tick += 1
            matched: List[_PrefixNode] = []
            level = self._root
            for d in digs:
                node = level.get(d)
                if node is None:
                    break
                if self._alloc.gen_of(node.page) != node.gen:
                    # the cache holds a ref, so the generation CANNOT
                    # have moved — this is a double-release elsewhere
                    raise KvPageError(
                        f"prefix cache generation skew on page "
                        f"{node.page}")
                node.tick = self._tick
                matched.append(node)
                level = node.children
            pages = [n.page for n in matched]
            for p in pages:
                self._alloc.ref(p)
        if digs and len(matched) == len(digs):
            self.hits += 1
            count_prefix("prefix_hit")
        elif matched:
            self.partial_hits += 1
            count_prefix("prefix_partial_hit")
        else:
            self.misses += 1
            count_prefix("prefix_miss")
        return pages, len(pages) * self._page

    # -- insert (after an uncached admit's prefill) ------------------------

    def insert(self, ctx_tokens, page_ids) -> int:
        """Cache the full pages of a freshly prefilled context.
        ``page_ids[i]`` must hold chunk ``i``'s KV rows.  Takes one
        cache-owned ref per NEW node; returns how many were new."""
        digs = self._digests(ctx_tokens)
        new = 0
        with self._lock:
            self._tick += 1
            level = self._root
            parent: Optional[_PrefixNode] = None
            for i, d in enumerate(digs):
                node = level.get(d)
                if node is None:
                    page = page_ids[i]
                    self._alloc.ref(page)
                    node = _PrefixNode(d, page,
                                       self._alloc.gen_of(page),
                                       parent, self._tick)
                    level[d] = node
                    self._nodes += 1
                    new += 1
                node.tick = self._tick
                parent = node
                level = node.children
        if new:
            self.inserts += new
            count_prefix("prefix_insert")
            self.evict_to_budget()
        return new

    # -- eviction (leaf-first LRU) -----------------------------------------

    def _leaves_locked(self) -> List[_PrefixNode]:
        leaves: List[_PrefixNode] = []
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                leaves.append(n)
        return leaves

    def evict_lru(self) -> bool:
        """Drop the least-recently-touched LEAF (parents are never
        younger than a live child, so the tree stays a prefix set)."""
        with self._lock:
            leaves = self._leaves_locked()
            if not leaves:
                return False
            victim = min(leaves, key=lambda n: n.tick)
            siblings = victim.parent.children if victim.parent \
                else self._root
            del siblings[victim.digest]
            self._nodes -= 1
            page = victim.page
        self._alloc.release(page)
        self.evictions += 1
        count_prefix("prefix_evict")
        return True

    def evict_to_budget(self) -> int:
        if self._budget is None:
            return 0
        n = 0
        while self.held_pages() > self._budget and self.evict_lru():
            n += 1
        return n

    def evict_all(self) -> int:
        n = 0
        while self.evict_lru():
            n += 1
        return n

    def held_pages(self) -> int:
        with self._lock:
            return self._nodes

    def stats(self) -> Dict[str, int]:
        return {"nodes": self.held_pages(),
                "hits": self.hits,
                "partial_hits": self.partial_hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions}


# ---------------------------------------------------------------------------
# Host tier — pinned host-RAM pool the cold sessions spill into
# ---------------------------------------------------------------------------

# every live HostPagePool, so the drain plane can count in-flight
# spills without the pools' owners registering anything
_host_pools: "weakref.WeakSet" = weakref.WeakSet()


class HostHandle:
    """One staged page in the host tier (slot + generation + size —
    the shm ring's descriptor shape, host-RAM flavored)."""

    __slots__ = ("slot", "gen", "nbytes")

    def __init__(self, slot: int, gen: int, nbytes: int):
        self.slot = slot
        self.gen = gen
        self.nbytes = nbytes


class HostPagePool:
    """Fixed-slot pinned host-RAM pool for evicted KV pages.

    The shm ring's slot discipline applied to the eviction tier: a
    fixed preallocated buffer (no growth, exhaustion is a NAMED close
    reason), one memcpy per staged page (audited under the
    ``spill_host`` stage), generation-checked handles, and loud
    double-free.  ``begin_spill``/``end_spill`` bracket a whole
    session's spill so the drain plane can count evictions in flight;
    ``drain_abort`` marks the pool dead at drain-grace expiry and
    refuses new spills from then on.
    """

    def __init__(self, slots: int, slot_bytes: int):
        import numpy as np
        self._np = np
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._buf = np.zeros((self.slots, self.slot_bytes),
                             dtype=np.uint8)
        self._lock = threading.Lock()
        self._free = list(range(self.slots))
        self._gen = [0] * self.slots
        self._live = [False] * self.slots
        self._inflight = 0
        self._abort_reason: Optional[str] = None
        self.staged = 0
        self.fetched = 0
        self.peak_slots_used = 0
        _host_pools.add(self)

    # -- spill bracketing (the drain gauge) --------------------------------

    def begin_spill(self) -> bool:
        """Open one spill bracket; False once the pool is aborted (the
        caller must close the session under the abort reason)."""
        with self._lock:
            if self._abort_reason is not None:
                return False
            self._inflight += 1
            return True

    def end_spill(self) -> None:
        with self._lock:
            self._inflight -= 1
            assert self._inflight >= 0, "unbalanced kv spill bracket"

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain_abort(self, reason: str) -> None:
        assert reason in KV_EVICT_REASONS, reason
        with self._lock:
            self._abort_reason = reason

    def abort_reason(self) -> Optional[str]:
        with self._lock:
            return self._abort_reason

    # -- stage / fetch / free ----------------------------------------------

    def stage(self, src) -> Optional[HostHandle]:
        """Land one page's bytes in a slot — the tier's ONE memcpy per
        page.  ``src`` is a host uint8 view (<= slot_bytes).  None when
        the tier is full (the caller closes under
        ``kv_host_tier_full``)."""
        nb = src.nbytes
        if nb > self.slot_bytes:
            raise KvPageError(
                f"kv spill page of {nb} bytes exceeds host slot "
                f"({self.slot_bytes})")
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._gen[slot] += 1
            gen = self._gen[slot]
            self._live[slot] = True
            used = self.slots - len(self._free)
            if used > self.peak_slots_used:
                self.peak_slots_used = used
        self._np.copyto(self._buf[slot, :nb],
                        src.reshape(-1).view(self._np.uint8))
        from ..butil import copy_audit
        if copy_audit.enabled and nb >= copy_audit.AUDIT_FLOOR:
            copy_audit.record("spill_host", nb)
        with self._lock:
            self.staged += 1
        return HostHandle(slot, gen, nb)

    def fetch(self, h: HostHandle):
        """Read a staged page back (generation-checked view — the
        caller devices-put it and then frees the slot)."""
        with self._lock:
            if not (0 <= h.slot < self.slots) \
                    or not self._live[h.slot] \
                    or self._gen[h.slot] != h.gen:
                raise KvPageError(
                    f"stale kv host fetch (slot {h.slot} gen {h.gen})")
            self.fetched += 1
        return self._buf[h.slot, :h.nbytes]

    def free(self, h: HostHandle) -> None:
        with self._lock:
            if not (0 <= h.slot < self.slots) \
                    or not self._live[h.slot] \
                    or self._gen[h.slot] != h.gen:
                raise KvPageError(
                    f"double/stale kv host free (slot {h.slot} gen "
                    f"{h.gen})")
            self._live[h.slot] = False
            self._free.append(h.slot)

    def slots_free(self) -> int:
        with self._lock:
            return len(self._free)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"slots": self.slots,
                    "slot_bytes": self.slot_bytes,
                    "free": len(self._free),
                    "inflight": self._inflight,
                    "staged": self.staged,
                    "fetched": self.fetched,
                    "peak_slots_used": self.peak_slots_used}


def host_inflight_spills() -> int:
    """Host-tier spills currently in flight across every live pool —
    the drain plane's second gauge (0 when no host tier exists)."""
    return sum(pool.inflight() for pool in list(_host_pools))
