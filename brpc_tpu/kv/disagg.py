"""Disaggregated prefill/decode serving — the two-tier LM service.

The fabric-lib shape (PAPERS.md): LLM serving at scale splits prompt
processing (prefill — compute-bound, long bursts) from token
generation (decode — memory-bound, long-lived sessions), scales the
tiers independently, and moves each session's KV-cache between them as
registered memory.  Here:

- :class:`PrefillService` serves the SAME ``LM.Decode`` wire contract
  as the monolithic service: it accepts the client's stream, runs the
  bucketed prompt prefill, exports the session's cache as KV pages and
  hands the LIVE session to the decode tier mid-request through
  :class:`~brpc_tpu.kv.transport.KvTransport`.  On any named handoff
  fallback it decodes locally (the monolithic path — the client never
  sees the topology), or, in strict mode, closes the stream with the
  named ``kv_handoff_failed`` reason.
- :class:`DecodeTierService` is the decode tier's handoff surface
  (``KV.Probe`` + ``KV.ImportSession``): imports the pages, drops them
  into a continuous-batcher slot between steps
  (:meth:`ContinuousBatcher.join_imported`), and the session's tokens
  stream to the ORIGINAL client over the stream it already holds — on
  a native server, the engine's kind-5 lane.

Token identity with the monolithic path is by construction, not luck:
both tiers run the ONE ``bucketed_prefill`` and the one batch-step
program, so a handed-off session emits bit-identical tokens (pinned by
``tests/test_kv_disagg.py``).

Topology note: stream adoption uses the process-global stream registry,
so the decode tier must be co-resident with the prefill tier's process
to take over the client stream directly (the same-host deployment this
round ships).  A cross-process decode tier answers
``kv_stream_not_local`` and the prefill tier decodes locally — a relay
(prefill forwarding the decode tier's chunks) is the named follow-up in
ROADMAP item 4, not a silent behavior change.
"""

from __future__ import annotations

import struct
import threading
from typing import Any, Optional

from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..models.lm_service import LMService, bucketed_prefill
from ..models.transformer_lm import (decode_cache_from_pages,
                                     export_decode_cache, kv_page_specs)
from ..server.service import Service
from .pages import KvPageError
from .transport import (KvTransport, decode_manifest,
                        encode_probe_response, import_pages,
                        stream_auth)


class DecodeTierService(Service):
    """``KV.Probe`` — lane-capability handshake; ``KV.ImportSession`` —
    adopt a prefilled session into the continuous batch.  Wraps the
    tier's :class:`LMService` (which may also serve ``LM.Decode``
    directly: a decode tier is a superset of a monolithic server)."""

    def __init__(self, lm: LMService):
        self.lm = lm

    @classmethod
    def service_name(cls) -> str:
        return "KV"

    def Probe(self, cntl, request):
        # capability answer + the fleet load-report tail (versioned,
        # ignored by pre-fleet probers): the prefill tier's admission /
        # LB side reads live slot availability from the same handshake
        # it already makes before moving a byte
        try:
            from .. import fleet
            report = fleet.report_cache().get(getattr(cntl, "server",
                                                      None))
        except Exception:
            report = None
        return encode_probe_response(report=report)

    def ImportSession(self, cntl, request):
        from time import monotonic_ns

        from ..streaming import find_stream
        recv_ns = monotonic_ns()
        try:
            man = decode_manifest(bytes(request))
        except (KvPageError, struct.error) as e:
            cntl.set_failed(Errno.EREQUEST,
                            f"kv_import_rejected: bad manifest: {e}")
            return None
        if man.model_fp != self.lm.model_fingerprint():
            cntl.set_failed(
                Errno.EREQUEST,
                "kv_model_mismatch: this tier serves "
                f"{self.lm.model_fingerprint().decode()!r}")
            return None
        if not (0 < man.max_new <= self.lm.max_new_cap) \
                or man.ctx_len + 1 + man.max_new > self.lm.cfg.max_seq \
                or not (0 <= man.last_token < self.lm.cfg.vocab):
            cntl.set_failed(Errno.EREQUEST,
                            "kv_import_rejected: session bounds")
            return None
        if man.auth != stream_auth(man.stream_id):
            # stream ids are enumerable; adopting one requires the
            # process-keyed tag only a co-resident tier can mint — a
            # forged manifest naming another client's live stream is
            # refused here, before any page resolves
            cntl.set_failed(Errno.EREQUEST,
                            "kv_stream_not_local: stream "
                            f"{man.stream_id} is not adoptable here")
            return None
        stream = find_stream(man.stream_id)
        if stream is None or stream.closed:
            # the client stream is not adoptable from this process —
            # the sender falls back to local decode under this reason
            cntl.set_failed(Errno.EREQUEST,
                            "kv_stream_not_local: stream "
                            f"{man.stream_id} is not resolvable here")
            return None
        try:
            arrays = import_pages(man, cntl.request_attachment,
                                  kv_page_specs(self.lm.cfg))
            cache1 = decode_cache_from_pages(self.lm.cfg, arrays)
        except KvPageError as e:
            # LOUD failure is the contract: a stale/double import must
            # fail the handoff RPC (sender keeps the session), never
            # seat a session on an empty cache
            cntl.set_failed(Errno.ERESPONSE,
                            f"kv_import_rejected: {e}")
            return None
        # decode-tier half of the stitched trace: the handoff RPC
        # carried the prefill request's trace id in its ordinary trace
        # TLVs, so cntl.span (when present) is already forced under
        # that id — the session span is its child, backdated to the
        # import's arrival so the transfer+import time it covers is
        # honest (rpcz.backdate_span, the PR 4 stitcher's convention)
        span = None
        req_span = getattr(cntl, "span", None)
        if req_span is not None:
            from ..rpcz import Span, backdate_span
            span = Span("KV.DecodeTierSession",
                        trace_id=req_span.trace_id,
                        parent_span_id=req_span.span_id)
            span.remote_side = req_span.remote_side
            backdate_span(span, recv_ns)
        meta = getattr(cntl, "request_meta", None)
        tenant = getattr(meta, "tenant", b"") if meta is not None \
            else b""
        self.lm.batcher().join_imported(stream, man.last_token,
                                        man.ctx_len, man.max_new,
                                        cache1, tenant=tenant,
                                        span=span)
        return b"ok"


class PrefillService(LMService):
    """The prefill tier: ``LM.Decode``-compatible, but the decode half
    of every session is handed to a decode tier through the KV
    transfer plane.  ``Generate``/``Info`` are inherited unchanged (a
    prefill tier still answers unary completions itself).

    ``fallback_local=True`` (default) keeps the monolithic behavior on
    ANY named handoff fallback — capacity planning can then read the
    ``kv_fallback_counters`` to see what the fleet is declining.
    Strict tiers (``fallback_local=False``) refuse instead: stream
    closed with the named ``kv_handoff_failed`` reason, EINTERNAL on
    the RPC."""

    def __init__(self, *args, decode_channel=None,
                 transport: Optional[KvTransport] = None,
                 fallback_local: bool = True, **kw):
        super().__init__(*args, **kw)
        self.decode_channel = decode_channel
        self.transport = transport or KvTransport()
        self.fallback_local = fallback_local
        self._prefill_j = None
        self._prefill_lock = threading.Lock()

    def _ensure_prefill(self):
        with self._prefill_lock:
            if self._prefill_j is None:
                import functools

                import jax

                from ..models.transformer_lm import make_decode
                prefill, _step = make_decode(self.cfg)
                self._prefill_j = jax.jit(
                    functools.partial(prefill, self.params))
            return self._prefill_j

    def Decode(self, cntl, request):
        parsed = self._check_decode_request(cntl, request)
        if parsed is None:
            return None
        prompt, max_new, stream = parsed
        # prefill-tier half of the stitched trace: a traced Decode
        # gets a forced session span whose chunk-slice event covers
        # the whole-prompt prefill this tier runs
        span = self._session_span(cntl)
        if span is not None:
            span.annotate("lm_join")
        cache1, ctx_len = bucketed_prefill(self._ensure_prefill(),
                                           self.cfg, prompt[0])
        if span is not None:
            span.annotate("lm_chunk_slice")
        last_token = int(prompt[0][-1])
        pages = export_decode_cache(self.cfg, cache1)
        res = self.transport.handoff(
            self.decode_channel, stream.id, ctx_len, last_token,
            max_new, self.model_fingerprint(), pages,
            owner=("kv", cntl.socket_id),
            trace=(span.trace_id, span.span_id)
            if span is not None else None)
        if res.ok:
            if span is not None:
                span.annotate("lm_handoff")
                span.finish(0)
            return struct.pack("<I", max_new)
        if self.fallback_local and not res.ambiguous:
            # monolithic fallback: the SAME cache1 joins the local
            # batch, so the fallback is token-identical too (and free —
            # the prefill is never recomputed).  Only for failures that
            # PROVE the decode tier never seated the session: an
            # ambiguous one (timeout / transport death mid-import) may
            # have landed, and two batchers decoding onto one client
            # stream is the at-most-once violation — those close with
            # the named reason instead and the client retries
            LOG.info("kv handoff fell back to local decode (%s)",
                     res.reason)
            meta = getattr(cntl, "request_meta", None)
            tenant = getattr(meta, "tenant", b"") \
                if meta is not None else b""
            self.batcher().join_imported(stream, last_token, ctx_len,
                                         max_new, cache1,
                                         tenant=tenant, span=span)
            return struct.pack("<I", max_new)
        stream.close(reason="kv_handoff_failed")
        try:
            from .. import fleet
            fleet.record_event("fleet_kv_handoff_failed",
                               str(res.reason))
        except Exception:
            pass
        if span is not None:
            span.annotate("lm_evict:kv_handoff_failed")
            span.finish(int(Errno.EINTERNAL))
        cntl.set_failed(Errno.EINTERNAL,
                        f"kv handoff failed: {res.reason}")
        return None
