"""KvTransport — move a session's KV pages to a peer over the cheapest
lane it can actually reach.

Three lanes, probed per peer and chosen per handoff:

    ici    the peers share one JAX runtime (domain-token match — two
           tiers in one process, or a single-controller slice): pages
           are already registered on the in-process fabric at export,
           so the wire carries 12-byte descriptors and the import is an
           alias.  Zero payload bytes through the message path, zero
           copies on either ledger.
    shm    same host, different process: each page's bytes are staged
           into the process tx ring (ONE memcpy — the round-11 shm
           discipline) and the wire carries 24-byte ring descriptors;
           the importer maps the ring and lands the pages device-side.
    copy   the fallback — page bytes ride the handoff RPC's attachment
           (the serialized message path).  Correct everywhere, and
           every arrival here is counted under a NAMED reason from the
           closed enum below: there is no "unknown" bucket, so a lane
           regression shows up as a counter, not a mystery slowdown.

The handoff RPC itself (``KV.ImportSession``) is an ordinary unary
call: it rides whatever server lane the decode tier runs — on a native
tier that is the kind-3 slim lane, which binds the compiled interceptor
chain, so handoffs pass admission/deadline/trace like any other
request.  Only the page PAYLOAD is special-cased off the message path.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from .pages import KvPageError, process_kv_store

define_flag("kv_transfer_enabled", True,
            "move KV-cache pages by fabric/shm descriptor instead of "
            "serialized bytes (off = every handoff rides the copy "
            "lane under kv_disabled)",
            validator=lambda v: isinstance(v, bool))

# ---------------------------------------------------------------------------
# Closed reason enums (no "unknown" bucket — every handoff that does not
# ride the cheapest lane, and every session that falls back to local
# decode, increments exactly one of these; the static enum checker
# requires a test pin for each, tools/check/enums.py).
# ---------------------------------------------------------------------------

KV_FALLBACK_REASONS = (
    "kv_disabled",          # kv_transfer_enabled flag off -> copy lane
    "kv_probe_failed",      # peer never answered the capability probe
    "kv_model_mismatch",    # peer serves a different model fingerprint
    "kv_shm_unavailable",   # same host, but no shm ring in this sandbox
    "kv_page_over_slot",    # a page exceeds the ring slot size
    "kv_ring_exhausted",    # no free ring slots (sender backpressure)
    "kv_pages_exhausted",   # page export table full (backpressure)
    "kv_peer_remote",       # different host, no transfer fabric
    "kv_stream_not_local",  # client stream not adoptable by the peer
    "kv_import_rejected",   # peer refused/failed the import RPC
    "kv_no_decode_tier",    # no decode channel configured / reachable
)

# stream close reasons the kv plane can emit (strict tiers close the
# client stream with a NAMED reason instead of decoding locally)
KV_CLOSE_REASONS = (
    "kv_handoff_failed",
)

_fb_lock = threading.Lock()
_fallbacks: Dict[str, int] = {r: 0 for r in KV_FALLBACK_REASONS}


def count_fallback(reason: str) -> None:
    assert reason in _fallbacks, f"unnamed kv fallback {reason!r}"
    with _fb_lock:
        _fallbacks[reason] += 1


def kv_fallback_counters() -> Dict[str, int]:
    with _fb_lock:
        return dict(_fallbacks)


_stats_lock = threading.Lock()
_stats = {"sessions": 0, "ici_sessions": 0, "shm_sessions": 0,
          "copy_sessions": 0, "local_fallbacks": 0, "pages_moved": 0,
          "bytes_moved": 0}


def _stat(key: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[key] += n


def kv_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(_stats)


def _reset_for_tests() -> None:
    with _fb_lock:
        for k in _fallbacks:
            _fallbacks[k] = 0
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


# ---------------------------------------------------------------------------
# Wire codecs — manifest + per-lane page descriptor lists.  The payload
# carries session METADATA and descriptors only; page bytes ride the
# lane (ici/shm) or, on the copy lane, the RPC attachment.
# ---------------------------------------------------------------------------

_MAGIC = b"KVH1"
LANE_ICI, LANE_SHM, LANE_COPY = 0, 1, 2
_LANE_NAMES = {LANE_ICI: "ici", LANE_SHM: "shm", LANE_COPY: "copy"}

_PROBE_MAGIC = b"KVP1"

# Stream-adoption authenticator: stream ids are enumerable enough (a
# random offset, then sequential) that "name a live stream id" must
# not suffice to seat a session on another client's stream.  The tag
# is keyed on a PROCESS secret — exactly the reach of direct stream
# takeover (the decode tier must share the prefill tier's stream
# registry, i.e. the process), so a co-resident tier can always mint
# and verify it while a remote forger never can.  Same trust posture
# as the ici domain exchange: guards misconfiguration and cross-tenant
# reach, not a compromised process.
_STREAM_SECRET = os.urandom(16)
_AUTH_BYTES = 8


def stream_auth(stream_id: int) -> bytes:
    return hashlib.blake2b(struct.pack("<Q", stream_id),
                           key=_STREAM_SECRET,
                           digest_size=_AUTH_BYTES).digest()


class SessionManifest:
    __slots__ = ("lane", "stream_id", "auth", "ctx_len", "last_token",
                 "max_new", "model_fp", "descs")

    def __init__(self, lane: int, stream_id: int, auth: bytes,
                 ctx_len: int, last_token: int, max_new: int,
                 model_fp: bytes, descs: List[bytes]):
        self.lane = lane
        self.stream_id = stream_id
        self.auth = auth
        self.ctx_len = ctx_len
        self.last_token = last_token
        self.max_new = max_new
        self.model_fp = model_fp
        self.descs = descs


def encode_manifest(m: SessionManifest) -> bytes:
    out = [_MAGIC, struct.pack("<BQ", m.lane, m.stream_id),
           m.auth,
           struct.pack("<IiIH", m.ctx_len, m.last_token, m.max_new,
                       len(m.model_fp)), m.model_fp,
           struct.pack("<H", len(m.descs))]
    for d in m.descs:
        out.append(struct.pack("<H", len(d)))
        out.append(d)
    return b"".join(out)


def decode_manifest(data: bytes) -> SessionManifest:
    if data[:4] != _MAGIC:
        raise KvPageError("bad kv manifest magic")
    lane, sid = struct.unpack_from("<BQ", data, 4)
    off = 4 + struct.calcsize("<BQ")
    auth = bytes(data[off:off + _AUTH_BYTES])
    off += _AUTH_BYTES
    ctx_len, last_tok, max_new, fplen = \
        struct.unpack_from("<IiIH", data, off)
    off += struct.calcsize("<IiIH")
    fp = bytes(data[off:off + fplen])
    off += fplen
    (nd,) = struct.unpack_from("<H", data, off)
    off += 2
    descs = []
    for _ in range(nd):
        (dl,) = struct.unpack_from("<H", data, off)
        off += 2
        descs.append(bytes(data[off:off + dl]))
        off += dl
    if off != len(data):
        raise KvPageError("trailing bytes in kv manifest")
    return SessionManifest(lane, sid, auth, ctx_len, last_tok, max_new,
                           fp, descs)


def encode_probe_response(report: Optional[dict] = None) -> bytes:
    """The decode tier's capability answer: fabric domain token, host
    token, shm availability — everything the sender needs to pick the
    cheapest lane BEFORE moving a byte.

    When ``report`` is given (a ``fleet.build_load_report`` dict), a
    versioned load-report tail is APPENDED after the capability
    fields: ``<I len> + json``.  Old decoders stop at the shm byte and
    never look at trailing bytes, so the extension is wire-compatible
    in both directions (old server → new client: no tail, report is
    None; new server → old client: tail ignored)."""
    from ..ici.fabric import local_domain_id
    from ..transport import shm_ring
    dom = local_domain_id()
    host = shm_ring._host_token()
    out = (_PROBE_MAGIC
           + struct.pack("<H", len(dom)) + dom
           + struct.pack("<H", len(host)) + host
           + struct.pack("<B", 1 if shm_ring.lane_enabled() else 0))
    if report is not None:
        blob = json.dumps(report, default=str).encode("utf-8")
        out += struct.pack("<I", len(blob)) + blob
    return out


def decode_probe_response(data: bytes):
    """-> (domain, host, shm_ok) or None (not a kv-capable peer)."""
    try:
        if data[:4] != _PROBE_MAGIC:
            return None
        (dl,) = struct.unpack_from("<H", data, 4)
        off = 6
        dom = bytes(data[off:off + dl])
        off += dl
        (hl,) = struct.unpack_from("<H", data, off)
        off += 2
        host = bytes(data[off:off + hl])
        off += hl
        (shm_ok,) = struct.unpack_from("<B", data, off)
        return dom, host, bool(shm_ok)
    except struct.error:
        return None


def decode_probe_report(data: bytes) -> Optional[dict]:
    """The versioned load-report tail of a KV.Probe response, or None
    (pre-fleet peer / no tail / malformed tail).  Capability parsing
    above is unaffected either way."""
    try:
        if data[:4] != _PROBE_MAGIC:
            return None
        (dl,) = struct.unpack_from("<H", data, 4)
        off = 6 + dl
        (hl,) = struct.unpack_from("<H", data, off)
        off += 2 + hl + 1                      # host + shm byte
        if off + 4 > len(data):
            return None
        (rl,) = struct.unpack_from("<I", data, off)
        off += 4
        if rl == 0 or off + rl > len(data):
            return None
        report = json.loads(data[off:off + rl].decode("utf-8"))
        return report if isinstance(report, dict) else None
    except (struct.error, ValueError, UnicodeDecodeError):
        return None


def _host_view(array):
    """A device page's bytes as a read-only host view (the shm/copy
    lanes' D2H staging; the ici lane never calls this)."""
    import numpy as np
    a = np.ascontiguousarray(np.asarray(array))
    return memoryview(a).cast("B")


# ---------------------------------------------------------------------------
# The transport
# ---------------------------------------------------------------------------

class HandoffResult:
    __slots__ = ("ok", "lane", "reason", "ambiguous")

    def __init__(self, ok: bool, lane: Optional[str],
                 reason: Optional[str], ambiguous: bool = False):
        self.ok = ok            # the peer imported the session
        self.lane = lane        # "ici" / "shm" / "copy" when ok
        self.reason = reason    # named fallback reason (lane demotion
        #                         or handoff failure), None on a clean
        #                         cheapest-lane handoff
        # the failure does NOT prove the peer never seated the session
        # (timeout / transport death after the import may have landed):
        # the caller must NOT decode locally — two batchers writing one
        # client stream is the at-most-once violation.  False only for
        # failures that provably precede the join (no RPC attempted, or
        # a clean application-level refusal from the import handler).
        self.ambiguous = ambiguous


class KvTransport:
    """Per-process handoff client: probes peers once per channel,
    exports/stages pages on the cheapest reachable lane, settles every
    lease whatever the outcome."""

    # probe-cache lifetimes: capabilities are near-static (re-probed
    # occasionally in case a peer restarted with different ones), but a
    # FAILED probe must retry fast — a decode tier that was briefly
    # unreachable at first contact must not be written off for the
    # process lifetime with only a counter as evidence
    PROBE_OK_TTL_S = 60.0
    PROBE_FAIL_TTL_S = 2.0

    def __init__(self, probe_timeout_ms: int = 5_000,
                 import_timeout_ms: int = 30_000,
                 force_lane: Optional[str] = None):
        self.probe_timeout_ms = probe_timeout_ms
        self.import_timeout_ms = import_timeout_ms
        # tests/benches pin a lane ("ici"/"shm"/"copy") to measure it
        # in isolation; production leaves None (cheapest reachable)
        self.force_lane = force_lane
        self._peer_lock = threading.Lock()
        # weak-keyed: a GC'd channel must not alias its cache entry to
        # whatever new channel lands on the recycled id(), and dead
        # channels must not accumulate entries
        self._peers: "weakref.WeakKeyDictionary[Any, Tuple[Any, float]]" \
            = weakref.WeakKeyDictionary()

    # -- peer capability ---------------------------------------------------

    def peer_info(self, channel):
        """TTL-cached KV.Probe of ``channel``'s peer (None = not
        kv-capable / unreachable right now)."""
        now = time.monotonic()
        with self._peer_lock:
            hit = self._peers.get(channel)
            if hit is not None and now < hit[1]:
                return hit[0]
        from ..client import Controller
        info = None
        try:
            cntl = Controller()
            cntl.timeout_ms = self.probe_timeout_ms
            c = channel.call_method("KV.Probe", b"", cntl=cntl)
            if not c.failed:
                info = decode_probe_response(bytes(c.response))
        except Exception as e:
            LOG.info("kv probe failed: %s", e)
        ttl = self.PROBE_OK_TTL_S if info is not None \
            else self.PROBE_FAIL_TTL_S
        with self._peer_lock:
            self._peers[channel] = (info, now + ttl)
        return info

    # -- lane choice + page preparation ------------------------------------

    def _pick_lane(self, info) -> Tuple[int, Optional[str]]:
        """(lane, demotion_reason) — reason is None on the cheapest
        lane, else names WHY the cheaper lanes were ineligible."""
        from ..ici.fabric import in_process_fabric
        from ..transport import shm_ring
        dom, host, peer_shm = info
        if not bool(get_flag("kv_transfer_enabled")):
            return LANE_COPY, "kv_disabled"
        if self.force_lane is not None:
            return {"ici": LANE_ICI, "shm": LANE_SHM,
                    "copy": LANE_COPY}[self.force_lane], None
        if in_process_fabric().can_reach(dom):
            return LANE_ICI, None
        if host == shm_ring._host_token():
            if peer_shm and shm_ring.lane_enabled():
                return LANE_SHM, None
            return LANE_COPY, "kv_shm_unavailable"
        return LANE_COPY, "kv_peer_remote"

    def _prepare_pages(self, lane: int, pages, owner):
        """Stage/export each ``(array, nbytes)`` page for ``lane``.
        Returns (lane, descs, att, leases, reason) — the lane may
        DEMOTE to copy (named reason) when a page does not fit the
        chosen lane; leases must be settled by the caller.  Host bytes
        are materialized lazily: the ici lane never leaves the
        device."""
        from ..transport import shm_ring
        store = process_kv_store()
        descs: List[bytes] = []
        leases: List[Tuple[str, Any]] = []
        if lane == LANE_ICI:
            for array, nbytes in pages:
                h = store.export_array(array, nbytes, owner=owner)
                if h is None:
                    self._settle(leases)
                    return self._prepare_pages(
                        LANE_COPY, pages, owner)[:4] \
                        + ("kv_pages_exhausted",)
                descs.append(h.describe())
                leases.append(("page", h))
            return lane, descs, None, leases, None
        if lane == LANE_SHM:
            ring = shm_ring.process_tx_ring()
            if ring is None:
                return self._prepare_pages(LANE_COPY, pages, owner)[:4] \
                    + ("kv_shm_unavailable",)
            for array, nbytes in pages:
                if nbytes > ring.slot_bytes:
                    self._settle(leases)
                    return self._prepare_pages(
                        LANE_COPY, pages, owner)[:4] \
                        + ("kv_page_over_slot",)
                staged = shm_ring.stage_page(_host_view(array),
                                             owner=owner)
                if staged is None:
                    self._settle(leases)
                    return self._prepare_pages(
                        LANE_COPY, pages, owner)[:4] \
                        + ("kv_ring_exhausted",)
                desc, lease = staged
                descs.append(desc)
                leases.append(("slot", lease))
            return lane, descs, None, leases, None
        # copy lane: page bytes ride the attachment, concatenated; the
        # descriptor is just each page's length (order carries layout).
        # join() takes the views directly — one gather into the blob,
        # no per-page bytes() intermediate
        att_parts = []
        for array, nbytes in pages:
            descs.append(struct.pack("<I", nbytes))
            att_parts.append(_host_view(array))
        return LANE_COPY, descs, b"".join(att_parts), leases, None

    @staticmethod
    def _settle(leases) -> None:
        """Release every lease of a handoff attempt (sync response —
        success OR failure — proves the peer is done reading)."""
        from ..transport import shm_ring
        store = process_kv_store()
        for kind, lease in leases:
            try:
                if kind == "page":
                    store.release(lease.page_id, lease.gen)
                else:
                    shm_ring.client_complete(lease)
            except KvPageError:
                pass      # swept by a dead-owner sweep mid-handoff

    # -- the handoff -------------------------------------------------------

    def handoff(self, channel, stream_id: int, ctx_len: int,
                last_token: int, max_new: int, model_fp: bytes,
                pages, owner: Any = None,
                trace: Any = None) -> HandoffResult:
        """Hand one live session to ``channel``'s peer.  ``pages`` is
        the ordered ``(device_array, nbytes)`` list from the model's
        cache export.  ``trace`` (optional ``(trace_id, span_id)``)
        rides the ImportSession RPC's EXISTING trace TLVs, so the
        decode tier's half of the session lands under the prefill
        request's trace id — distributed rpcz stitching with no new
        wire format.  Never raises: a False result means the caller
        still owns the session (decode locally or close with a named
        reason) and every lease is settled."""
        if channel is None:
            count_fallback("kv_no_decode_tier")
            _stat("local_fallbacks")
            return HandoffResult(False, None, "kv_no_decode_tier")
        info = self.peer_info(channel)
        if info is None:
            count_fallback("kv_probe_failed")
            _stat("local_fallbacks")
            return HandoffResult(False, None, "kv_probe_failed")
        lane, reason = self._pick_lane(info)
        if reason is not None:
            count_fallback(reason)
        lane, descs, att, leases, demote = self._prepare_pages(
            lane, pages, owner)
        if demote is not None:
            count_fallback(demote)
            reason = demote
        m = SessionManifest(lane, stream_id, stream_auth(stream_id),
                            ctx_len, last_token, max_new, model_fp,
                            descs)
        from ..butil.status import Errno
        from ..client import Controller
        cntl = Controller()
        cntl.timeout_ms = self.import_timeout_ms
        if trace is not None:
            cntl.trace_id, cntl.span_id = trace
        try:
            c = channel.call_method("KV.ImportSession",
                                    encode_manifest(m), cntl=cntl,
                                    attachment=att if att else None)
            failed, err, code = c.failed, (c.error_text or ""), \
                c.error_code
        except Exception as e:
            failed, err, code = True, f"{type(e).__name__}: {e}", -1
        finally:
            self._settle(leases)
        if failed:
            why = err.split(":", 1)[0].strip()
            if why not in KV_FALLBACK_REASONS:
                why = "kv_import_rejected"
            count_fallback(why)
            _stat("local_fallbacks")
            # only a clean APPLICATION refusal (the import handler's
            # EREQUEST/ERESPONSE answer) proves the session was never
            # seated; a timeout or transport death may have landed
            # AFTER the join — the caller must not decode the session
            # a second time onto the same stream
            ambiguous = code not in (int(Errno.EREQUEST),
                                     int(Errno.ERESPONSE))
            return HandoffResult(False, None, why,
                                 ambiguous=ambiguous)
        nbytes = sum(p[1] for p in pages)
        _stat("sessions")
        _stat(f"{_LANE_NAMES[lane]}_sessions")
        _stat("pages_moved", len(pages))
        _stat("bytes_moved", nbytes)
        return HandoffResult(True, _LANE_NAMES[lane], reason)


# ---------------------------------------------------------------------------
# Import side (the decode tier's half, called by kv/disagg)
# ---------------------------------------------------------------------------

def import_pages(manifest: SessionManifest, attachment,
                 page_specs) -> List[Any]:
    """Resolve the manifest's descriptors into device arrays, one per
    page, per the manifest's lane.  ``page_specs`` is the model's
    ordered ``(shape, dtype, nbytes)`` list — layout comes from the
    model config, never from the wire.  Raises :class:`KvPageError`
    loudly on anything stale/malformed (the service answers ERESPONSE:
    a silent empty cache is the one forbidden outcome)."""
    import numpy as np

    from .pages import decode_desc
    if len(manifest.descs) != len(page_specs):
        raise KvPageError(
            f"page count mismatch ({len(manifest.descs)} descriptors "
            f"for {len(page_specs)} pages)")
    arrays: List[Any] = []
    if manifest.lane == LANE_ICI:
        store = process_kv_store()
        for d, (shape, dtype, nbytes) in zip(manifest.descs, page_specs):
            page_id, gen, n = decode_desc(d)
            if n != nbytes:
                raise KvPageError(
                    f"kv page size mismatch ({n} != {nbytes})")
            arrays.append(store.import_page(page_id, gen, n))
        return arrays
    if manifest.lane == LANE_SHM:
        import jax.numpy as jnp

        from ..transport import shm_ring
        for d, (shape, dtype, nbytes) in zip(manifest.descs, page_specs):
            parsed = shm_ring.decode_desc(d)
            if parsed is None:
                raise KvPageError("malformed shm kv page descriptor")
            rid, _slot, off, ln = parsed
            if ln != nbytes:
                raise KvPageError(
                    f"kv page size mismatch ({ln} != {nbytes})")
            view = shm_ring.resolve(rid, off, ln)
            if view is None:
                raise KvPageError("unresolvable shm kv page descriptor")
            host = np.frombuffer(view, dtype=dtype).reshape(shape)
            # land before returning: the ring slot recycles once the
            # handoff response settles, so the page must not remain a
            # borrowed view of it
            arrays.append(jnp.asarray(host))
        return arrays
    if manifest.lane == LANE_COPY:
        import jax.numpy as jnp
        blob = bytes(attachment) if attachment is not None else b""
        off = 0
        for d, (shape, dtype, nbytes) in zip(manifest.descs, page_specs):
            (n,) = struct.unpack("<I", d)
            if n != nbytes or off + n > len(blob):
                raise KvPageError("kv copy-lane page bounds mismatch")
            host = np.frombuffer(blob, dtype=dtype,
                                 offset=off, count=nbytes
                                 // np.dtype(dtype).itemsize
                                 ).reshape(shape)
            arrays.append(jnp.asarray(host))
            off += n
        if off != len(blob):
            raise KvPageError("trailing bytes in kv copy-lane blob")
        return arrays
    raise KvPageError(f"unknown kv lane {manifest.lane}")
