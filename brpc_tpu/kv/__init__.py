"""KV-cache transfer subsystem — disaggregated prefill/decode serving.

LLM serving at scale separates prompt processing (prefill) from token
generation (decode) and moves each session's KV-cache between the
tiers as *registered memory*, never as bytes squeezed through the
serialized message path (fabric-lib, PAPERS.md; the same attachment/
RDMA discipline the reference applies to tensor traffic — PARITY row
68b).  This package makes KV-cache pages first-class transferable
objects:

- :mod:`pages` — the export registry: a session's cache is a **page
  list** with an explicit RDMA-style lifecycle (export → describe →
  import → release), generation-checked like ``transport/shm_ring``'s
  slots, owner-swept on socket death, settled by the drain plane;
- :mod:`transport` — :class:`KvTransport` picks the cheapest lane per
  peer (in-process/ICI fabric descriptors, same-host shm ring slots,
  copy-lane attachment fallback) under the closed
  ``KV_FALLBACK_REASONS`` enum — per-reason telemetry, no "unknown"
  bucket;
- :mod:`disagg` — the two-tier service: :class:`PrefillService` runs
  the prompt, exports the pages and hands the LIVE session to a
  :class:`DecodeTierService` mid-request; tokens stream to the
  original client over the stream lane it already holds.
"""

from .pages import (KvPageError, KvPageHandle, KvPageStore,
                    drain_settle, on_socket_closed, outstanding_pages,
                    process_kv_store)
from .transport import (KV_CLOSE_REASONS, KV_FALLBACK_REASONS,
                        KvTransport, count_fallback,
                        kv_fallback_counters, kv_stats)

# the service layer pulls in the model stack (jax/numpy); keep it lazy
# so transport-plane importers (socket teardown sweeps) stay cheap
_LAZY = {"DecodeTierService": "disagg", "PrefillService": "disagg"}

__all__ = [
    "DecodeTierService", "PrefillService",
    "KvPageError", "KvPageHandle", "KvPageStore",
    "drain_settle", "on_socket_closed", "outstanding_pages",
    "process_kv_store",
    "KV_CLOSE_REASONS", "KV_FALLBACK_REASONS", "KvTransport",
    "count_fallback", "kv_fallback_counters", "kv_stats",
]


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module("." + _LAZY[name], __name__)
        return getattr(mod, name)
    raise AttributeError(name)
