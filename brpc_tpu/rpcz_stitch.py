"""Distributed rpcz — cross-process trace stitching.

A traced multi-chip fan-out leaves its spans scattered across
processes: the caller holds the root and one client span per branch
(each knowing its branch's ``remote_side``), every sub-server holds
the matching server span.  Per-process ``/rpcz`` cannot show that tree
— this module can:

- :func:`collect_trace` starts from the local SpanStore and follows
  client spans' ``remote_side`` over plain HTTP
  (``/rpcz?trace_id=X&format=json``) to pull each sub-process's spans,
  breadth-first with a hop budget, deduplicating by span id (span ids
  are random-seeded per process — see rpcz._span_seq — so cross-rank
  collisions are negligible).
- :func:`annotate_skew` flags wall-clock skew: a child that appears to
  START before its parent's receive time is physically impossible, so
  the child is tagged ``clock_skew_us`` instead of silently
  mis-ordering the tree.  Spans also carry a CLOCK_MONOTONIC anchor
  (``mono_ns``) — comparable across processes of ONE host — for
  external tools that want exact same-host ordering.
- :func:`build_tree` nests span ids under their parents (children
  ordered by receive time).
- :func:`to_chrome_trace` emits Chrome trace-event JSON that loads
  directly in Perfetto / chrome://tracing, one "process" track per
  source process.
- :func:`render_tree_text` draws the tree for the /rpcz portal page.

The collector is deliberately transport-simple (http.client, bounded
timeouts, best-effort per remote): stitching is an operator query, not
a serving-path dependency.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional

from .butil.logging_util import LOG
from .rpcz import global_span_store

# bounded fan-out: a trace that crossed more processes than this is
# truncated (noted in the result) rather than holding the portal open
DEFAULT_MAX_HOPS = 16
# ... and bounded WALL CLOCK: the worst case is not hop count but dead
# peers (each SYN-blackholed fetch waits out its full timeout), so the
# whole walk shares one budget — max_hops dead remotes must not hold
# the /rpcz handler (and, on an inline native server, its engine loop)
# for max_hops * timeout_s seconds
DEFAULT_BUDGET_S = 8.0


def fetch_remote_spans(remote: str, trace_id: int,
                       timeout_s: float = 2.0,
                       limit: int = 512) -> List[Dict]:
    """One hop of the collector: GET the peer's local span list for
    ``trace_id`` from its builtin portal.  Raises on transport errors —
    the caller decides whether a missing peer kills the stitch."""
    import http.client
    host, _, port = remote.rpartition(":")
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                      timeout=timeout_s)
    try:
        conn.request("GET", f"/rpcz?trace_id={trace_id:x}&format=json"
                            f"&limit={int(limit)}")
        resp = conn.getresponse()
        if resp.status != 200:
            raise ConnectionError(f"/rpcz on {remote}: HTTP {resp.status}")
        return json.loads(resp.read()).get("spans", [])
    finally:
        conn.close()


def locate_trace_root(fleet: str, trace_id: int,
                      timeout_s: float = 2.0) -> List[str]:
    """Ask a fleet registry host which member(s) report the ROOT span
    of ``trace_id`` (the /fleet trace index, fed by every member's
    load report).  Before this, a stitch could only BFS from a process
    that already held part of the trace — now any process can start
    from the registry and land on the root holder directly.  Raises on
    transport errors; returns [] when no member claims the root (TTL'd
    out of the members' bounded root lists, or never traced)."""
    import http.client
    host, _, port = str(fleet).rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        conn.request("GET", f"/fleet?trace_id={trace_id:x}")
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"/fleet on {fleet}: HTTP {resp.status}")
        return list(json.loads(data.decode("utf-8",
                                           "replace")).get("owners", []))
    finally:
        conn.close()


def collect_trace_via_fleet(fleet: str, trace_id: int,
                            **kwargs) -> Dict:
    """Fleet-seeded stitch: locate the root-holding member(s) through
    the registry's trace index, then run :func:`collect_trace` with
    those instances pre-seeded on the BFS frontier (the local store
    still contributes whatever it holds).  A dead or index-less
    registry degrades to the plain local-seeded walk."""
    try:
        seeds = locate_trace_root(fleet, trace_id,
                                  timeout_s=kwargs.get("timeout_s", 2.0))
    except Exception as e:
        LOG.warning("rpcz stitch: fleet index %s failed: %s", fleet, e)
        seeds = []
    return collect_trace(trace_id, seed_remotes=seeds, **kwargs)


def collect_trace(trace_id: int, limit: int = 512,
                  max_hops: int = DEFAULT_MAX_HOPS,
                  timeout_s: float = 2.0,
                  budget_s: float = DEFAULT_BUDGET_S,
                  fetch: Callable = fetch_remote_spans,
                  skip=(), seed_remotes=()) -> Dict:
    """Stitch one trace across processes.

    Returns ``{"spans": [describe-dicts + "source"], "remotes":
    {remote: "ok" | error}, "truncated": bool}``.  Local spans seed the
    walk; every client span's ``remote_side`` is fetched once (BFS),
    and spans fetched from a remote can add further remotes (deeper
    call trees).  A dead peer degrades to a partial stitch with the
    failure recorded, never an exception.  ``budget_s`` caps the walk's
    TOTAL wall clock (per-fetch timeouts are clamped to what remains);
    exceeding it truncates like ``max_hops`` does.

    ``skip``: addresses whose spans are ALREADY in the local store —
    the /rpcz handler passes its own listen address so a stitch
    launched from inside a traced process never RPCs itself (on a
    single-loop inline server that self-call would wait out its own
    timeout: the handler occupies the loop the fetch needs).

    ``seed_remotes``: addresses to place on the BFS frontier BEFORE
    any local client span is followed — the fleet trace index's way of
    starting the walk at the root-holding process
    (:func:`collect_trace_via_fleet`)."""
    spans: Dict[int, Dict] = {}

    def _ingest(records, source: str) -> List[str]:
        new_remotes = []
        for rec in records:
            sid = rec.get("span_id")
            if not isinstance(sid, int) or sid in spans:
                continue
            rec = dict(rec)
            rec["source"] = source
            spans[sid] = rec
            if rec.get("side") == "client" and rec.get("remote"):
                new_remotes.append(rec["remote"])
        return new_remotes

    frontier = list(seed_remotes)
    frontier += _ingest(
        [s.describe() for s in
         global_span_store().by_trace(trace_id, limit)], "local")
    visited = set(str(a) for a in skip)
    remotes: Dict[str, str] = {a: "self" for a in visited}
    truncated = False
    hops = 0
    deadline = time.monotonic() + max(0.1, budget_s)
    while frontier:
        remote = frontier.pop(0)
        if remote in visited:
            continue
        visited.add(remote)
        hops += 1
        left = deadline - time.monotonic()
        if hops > max_hops or left <= 0:
            truncated = True
            break
        try:
            fetched = fetch(remote, trace_id,
                            timeout_s=min(timeout_s, left),
                            limit=limit)
        except Exception as e:            # partial stitch beats no stitch
            LOG.warning("rpcz stitch: fetching %s failed: %s", remote, e)
            remotes[remote] = f"{type(e).__name__}: {e}"
            continue
        remotes[remote] = "ok"
        frontier.extend(_ingest(fetched, remote))
    out = sorted(spans.values(), key=lambda r: r.get("received_us", 0))
    annotate_skew(out)
    return {"spans": out, "remotes": remotes, "truncated": truncated}


def annotate_skew(spans: List[Dict]) -> None:
    """Tag children whose receive time precedes their parent's: across
    hosts the wall clocks are not one clock, and a stitched tree that
    silently re-ordered such spans would lie.  Mutates the dicts —
    adds ``clock_skew_us`` (how far into the past the child appears to
    have started relative to its parent)."""
    by_id = {s["span_id"]: s for s in spans if "span_id" in s}
    for s in spans:
        parent = by_id.get(s.get("parent_span_id") or 0)
        if parent is None:
            continue
        skew = parent.get("received_us", 0) - s.get("received_us", 0)
        if skew > 0:
            s["clock_skew_us"] = skew


def build_tree(spans: List[Dict]) -> List[Dict]:
    """Nested ``{"span_id": id, "children": [...]}`` forest: spans
    whose parent is absent (or 0) are roots; children are ordered by
    receive time.  Ids only — the flat span list stays the single copy
    of the data."""
    by_id = {s["span_id"]: s for s in spans if "span_id" in s}
    nodes = {sid: {"span_id": sid, "children": []} for sid in by_id}
    roots = []
    for sid, span in by_id.items():
        parent = span.get("parent_span_id") or 0
        if parent in nodes and parent != sid:
            nodes[parent]["children"].append(nodes[sid])
        else:
            roots.append(nodes[sid])

    def _key(node):
        return by_id[node["span_id"]].get("received_us", 0)

    for node in nodes.values():
        node["children"].sort(key=_key)
    roots.sort(key=_key)
    return roots


def to_chrome_trace(spans: List[Dict]) -> Dict:
    """Chrome trace-event JSON (the ``traceEvents`` object form) —
    loads in Perfetto / chrome://tracing.  One pid per source process,
    one complete ("X") event per span, ids/annotations in ``args``;
    annotations additionally render as instant events on the span's
    track."""
    events = []
    pids: Dict[str, int] = {}
    for s in spans:
        src = str(s.get("source", "local"))
        pid = pids.get(src)
        if pid is None:
            pid = pids[src] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": src}})
        tid = int(s.get("span_id", 0))
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_span_id": s.get("parent_span_id"),
            "error_code": s.get("error_code", 0),
            "request_size": s.get("request_size", 0),
            "response_size": s.get("response_size", 0),
            "remote": s.get("remote", ""),
        }
        if "clock_skew_us" in s:
            args["clock_skew_us"] = s["clock_skew_us"]
        events.append({
            "ph": "X",
            "name": f"{s.get('side', '?')} {s.get('method', '?')}",
            "cat": s.get("side", "span"),
            "ts": s.get("received_us", 0),
            "dur": max(1, int(s.get("latency_us", 1))),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        for ann in s.get("annotations", ()):
            events.append({
                "ph": "i", "s": "t",
                "name": str(ann.get("text", ""))[:120],
                "ts": ann.get("us", s.get("received_us", 0)),
                "pid": pid, "tid": tid,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_tree_text(spans: List[Dict]) -> str:
    """Human-readable tree for the /rpcz portal page."""
    by_id = {s["span_id"]: s for s in spans if "span_id" in s}
    lines = [f"{len(spans)} span(s)"]

    def _fmt(s: Dict) -> str:
        err = f" ERR={s['error_code']}" if s.get("error_code") else ""
        skew = f" SKEW={s['clock_skew_us']}us" \
            if s.get("clock_skew_us") else ""
        remote = f" -> {s['remote']}" if s.get("remote") else ""
        return (f"{s.get('side', '?'):6s} {s.get('method', '?')}"
                f"{remote}  {s.get('latency_us', 0)}us"
                f"  [{s.get('source', 'local')}]{err}{skew}")

    def _walk(node: Dict, prefix: str, last: bool) -> None:
        tee = "`- " if last else "|- "
        lines.append(prefix + tee + _fmt(by_id[node["span_id"]]))
        child_prefix = prefix + ("   " if last else "|  ")
        kids = node["children"]
        for i, kid in enumerate(kids):
            _walk(kid, child_prefix, i == len(kids) - 1)

    roots = build_tree(spans)
    for i, root in enumerate(roots):
        _walk(root, "", i == len(roots) - 1)
    return "\n".join(lines) + "\n"
