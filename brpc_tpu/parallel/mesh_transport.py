"""MeshTransport — collectives over an ICI device mesh.

The TPU-native re-expression of the reference's transport matrix
(SURVEY.md §5.8): instead of per-peer sockets, peers form a mesh and data
moves through XLA collectives compiled onto the interconnect. The API is
shaped by what the RPC layers above need:

- ``scatter``/``gather``: host staging ↔ sharded device residency (the
  PartitionChannel data path);
- ``all_gather``/``reduce_scatter``/``psum``: fan-out merge semantics
  (ParallelChannel's ResponseMerger, reduced on-device);
- ``ring_shift``/``ring_exchange``: neighbor schedules (streaming windows
  and ring-attention building blocks);
- ``all_to_all``: resharding between partition schemes
  (DynamicPartitionChannel's migration).

All programs are built once per (shape, dtype) via jit caching; static
shapes keep XLA happy (SURVEY.md lesson: no data-dependent control flow
inside jit).
"""

from __future__ import annotations

import functools
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from ..butil.endpoint import EndPoint

_jax = None
# RLock: global_mesh_transport() holds it while MeshTransport.__init__
# re-enters via _jax_mod()
_lock = threading.RLock()


def _jax_mod():
    """Late import so pure-RPC users never pay for (or require) JAX."""
    global _jax
    with _lock:
        if _jax is None:
            import jax
            _jax = jax
        return _jax


def _shard_map(jax):
    """jax.shard_map (0.8+) or the experimental fallback; the VMA /
    replication check is off because collective outputs (psum/all_gather)
    are intentionally replicated across the axis."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm  # noqa
    try:
        return functools.partial(sm, check_vma=False)
    except TypeError:                                    # older signature
        return functools.partial(sm, check_rep=False)


def default_mesh(axis_name: str = "ici", devices=None):
    """1-D mesh over all local devices — the 'every chip is a peer' view."""
    jax = _jax_mod()
    from jax.sharding import Mesh
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis_name,))


class MeshTransport:
    """Collective schedules over one mesh axis.

    ≈ role of Socket+RdmaEndpoint for peers on the interconnect: the unit
    of addressing is the device coordinate (EndPoint ``ici://mesh/i``),
    the unit of transfer is an array shard, and "flow control" is XLA's
    static schedule rather than window+ack (SURVEY.md §5.8)."""

    def __init__(self, mesh=None, axis: str = "ici", name: str = "mesh0"):
        jax = _jax_mod()
        self.jax = jax
        self.mesh = mesh if mesh is not None else default_mesh(axis)
        self.axis = axis if axis in self.mesh.axis_names \
            else self.mesh.axis_names[0]
        self.name = name

    # -- addressing --------------------------------------------------------

    @property
    def n_peers(self) -> int:
        return int(self.mesh.devices.size)

    def endpoint(self, index: int) -> EndPoint:
        return EndPoint(mesh=self.name, device_index=index)

    def endpoints(self) -> Sequence[EndPoint]:
        return [self.endpoint(i) for i in range(self.n_peers)]

    # -- residency ---------------------------------------------------------

    def scatter(self, array, axis: int = 0):
        """Host/replicated array → sharded along ``axis`` across peers
        (the PartitionChannel write path)."""
        jax = self.jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = [None] * np.ndim(array)
        spec[axis] = self.axis
        return jax.device_put(array, NamedSharding(self.mesh, P(*spec)))

    def replicate(self, array):
        jax = self.jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(array, NamedSharding(self.mesh, P()))

    def gather(self, array) -> np.ndarray:
        """Sharded → host (the PartitionChannel read path)."""
        return np.asarray(self.jax.device_get(array))

    # -- collective programs (jit-cached per shape) -----------------------

    @functools.lru_cache(maxsize=256)
    def _ring_shift_fn(self, steps: int):
        jax = self.jax
        from jax.sharding import PartitionSpec as P
        n = self.mesh.shape[self.axis]
        perm = [(i, (i + steps) % n) for i in range(n)]

        def shift(x):
            return jax.lax.ppermute(x, self.axis, perm)

        return jax.jit(_shard_map(jax)(shift, mesh=self.mesh,
                                 in_specs=P(self.axis),
                                 out_specs=P(self.axis)))

    def ring_shift(self, x, steps: int = 1):
        """Every peer passes its shard ``steps`` neighbors down the ring
        (CollectivePermute on ICI — the streaming/pipeline primitive)."""
        return self._ring_shift_fn(steps)(x)

    @functools.lru_cache(maxsize=256)
    def _all_gather_fn(self):
        jax = self.jax
        from jax.sharding import PartitionSpec as P

        def ag(x):
            return jax.lax.all_gather(x, self.axis, tiled=True)

        return jax.jit(_shard_map(jax)(ag, mesh=self.mesh,
                                 in_specs=P(self.axis), out_specs=P()))

    def all_gather(self, x):
        """Each peer ends with every shard (fan-in broadcast merge)."""
        return self._all_gather_fn()(x)

    @functools.lru_cache(maxsize=256)
    def _psum_fn(self):
        jax = self.jax
        from jax.sharding import PartitionSpec as P

        def ps(x):
            return jax.lax.psum(x, self.axis)

        return jax.jit(_shard_map(jax)(ps, mesh=self.mesh,
                                 in_specs=P(self.axis), out_specs=P()))

    def psum(self, x):
        """Sum of all shards, replicated everywhere (ResponseMerger-as-
        reduction, computed on-device)."""
        return self._psum_fn()(x)

    @functools.lru_cache(maxsize=256)
    def _reduce_scatter_fn(self):
        jax = self.jax
        from jax.sharding import PartitionSpec as P

        def rs(x):
            # x per-device: (1, L). Sum across peers, each keeps chunk i
            # of the result (global out: (n, L/n)).
            return jax.lax.psum_scatter(x[0], self.axis,
                                        scatter_dimension=0,
                                        tiled=True)[None]

        return jax.jit(_shard_map(jax)(rs, mesh=self.mesh,
                                 in_specs=P(self.axis),
                                 out_specs=P(self.axis)))

    def reduce_scatter(self, x):
        """Row-sharded (n, L) input: result (n, L/n) — peer i holds the
        i-th chunk of the element-wise sum of all rows."""
        return self._reduce_scatter_fn()(x)

    @functools.lru_cache(maxsize=256)
    def _all_to_all_fn(self, split_axis: int, concat_axis: int):
        jax = self.jax
        from jax.sharding import PartitionSpec as P

        def a2a(x):
            return jax.lax.all_to_all(x, self.axis, split_axis=split_axis,
                                      concat_axis=concat_axis, tiled=True)

        return jax.jit(_shard_map(jax)(a2a, mesh=self.mesh,
                                 in_specs=P(self.axis),
                                 out_specs=P(self.axis)))

    def all_to_all(self, x, split_axis: int = 1, concat_axis: int = 0):
        """Transpose which dimension is sharded — the re-partitioning
        move (and the Ulysses-style sequence↔head exchange)."""
        return self._all_to_all_fn(split_axis, concat_axis)(x)

    # lru_cache on methods holds self; fine — transports are process-wide
    # singletons like the reference's EventDispatchers

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


_default_transport: Optional[MeshTransport] = None


def global_mesh_transport() -> MeshTransport:
    global _default_transport
    with _lock:
        if _default_transport is None:
            _default_transport = MeshTransport()
        return _default_transport
