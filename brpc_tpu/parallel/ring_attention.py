"""Sequence parallelism for long context: ring attention + Ulysses.

No counterpart exists in the reference (SURVEY.md §5.7 — brpc's answer
to big payloads is partition + streaming); this is new TPU-first design
on the collective transport, as the survey prescribes:

- **ring_attention**: Q stays put; K/V blocks rotate around the ``sp``
  ring via ppermute while a flash-style online softmax accumulates
  (running max / denominator), so attention over sequence length S runs
  with S/n residency per chip and compute/communication overlap left to
  XLA's schedule. Blockwise-parallel/ring formulation (public technique;
  fresh implementation).
- **ulysses_attention**: all_to_all re-shards sequence↔heads so each
  chip runs FULL-sequence attention for a head subset — cheaper at
  moderate S when heads divide the mesh.

Both are jittable shard_map programs over one mesh axis; causal masking
uses global positions derived from the device's ring index.
"""

from __future__ import annotations

import functools
from typing import Optional

from .mesh_transport import _shard_map


def _attention_block(q, k_blk, v_blk, scale, mask):
    """One (Q-local × K-block) flash step: returns (scores_max, exp
    scores, weighted values) pieces for the online softmax."""
    import jax.numpy as jnp

    # (b, sq, h, d) x (b, sk, h, d) -> (b, h, sq, sk) on the MXU
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    return s


def make_ring_attention(mesh, axis: str = "sp", causal: bool = False):
    """Build the jitted ring attention fn for ``mesh``/``axis``.

    Global shapes: q, k, v — (batch, seq, heads, dim), sharded on seq.
    Returns f(q, k, v) -> (batch, seq, heads, dim), same sharding.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(q, k, v):
        # per-device: (b, s_local, h, d)
        b, sl, h, d = q.shape
        scale = 1.0 / (d ** 0.5)
        idx = jax.lax.axis_index(axis)
        q_pos = idx * sl + jnp.arange(sl)              # global positions

        m0 = jnp.full((b, h, sl), -1e30, jnp.float32)  # running max
        l0 = jnp.zeros((b, h, sl), jnp.float32)        # running denom
        acc0 = jnp.zeros((b, sl, h, d), jnp.float32)

        def body(step, carry):
            k_blk, v_blk, m, l, acc = carry
            # block we currently hold started at device (idx - step) % n
            src = (idx - step) % n
            mask = None
            if causal:
                k_pos = src * sl + jnp.arange(sl)
                mask = q_pos[:, None] >= k_pos[None, :]   # (sq, sk)
                mask = mask[None, None]                   # (1,1,sq,sk)
            s = _attention_block(q, k_blk, v_blk, scale, mask)
            m_new = jnp.maximum(m, s.max(axis=-1))
            corr = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])             # (b,h,sq,sk)
            l = l * corr + p.sum(axis=-1)
            pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk,
                            preferred_element_type=jnp.float32)
            acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
            k_blk = jax.lax.ppermute(k_blk, axis, perm)
            v_blk = jax.lax.ppermute(v_blk, axis, perm)
            return (k_blk, v_blk, m_new, l, acc)

        _, _, m, l, acc = jax.lax.fori_loop(
            0, n, body, (k, v, m0, l0, acc0))
        out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    spec = P(None, axis, None, None)
    return jax.jit(_shard_map(jax)(local, mesh=mesh,
                                   in_specs=(spec, spec, spec),
                                   out_specs=spec))


def make_ulysses_attention(mesh, axis: str = "sp", causal: bool = False,
                           use_flash: bool = False):
    """Sequence↔head all_to_all, full local attention, exchange back.
    Heads must be divisible by the mesh axis size.  ``use_flash`` runs
    the local attention through the Pallas flash kernel
    (ops/flash_attention.py) — O(s) memory per chip instead of the
    dense (s, s) score matrix, which is what makes Ulysses viable at
    genuinely long context."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]

    def local(q, k, v):
        # in: (b, s/n, h, d) → a2a → (b, s, h/n, d)
        def seq_to_head(x):
            return jax.lax.all_to_all(x, axis, split_axis=2,
                                      concat_axis=1, tiled=True)

        def head_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        qf, kf, vf = seq_to_head(q), seq_to_head(k), seq_to_head(v)
        if use_flash:
            from ..ops.flash_attention import flash_attention
            out = flash_attention(qf, kf, vf, causal)
            return head_to_seq(out.astype(q.dtype))
        b, s, hh, d = qf.shape
        scale = 1.0 / (d ** 0.5)
        s_mat = jnp.einsum("bqhd,bkhd->bhqk", qf, kf,
                           preferred_element_type=jnp.float32) * scale
        if causal:
            pos = jnp.arange(s)
            mask = (pos[:, None] >= pos[None, :])[None, None]
            s_mat = jnp.where(mask, s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, vf,
                         preferred_element_type=jnp.float32)
        return head_to_seq(out.astype(q.dtype))

    spec = P(None, axis, None, None)
    return jax.jit(_shard_map(jax)(local, mesh=mesh,
                                   in_specs=(spec, spec, spec),
                                   out_specs=spec))


def reference_attention(q, k, v, causal: bool = False):
    """Dense single-device attention — the correctness oracle for tests
    (one implementation: ops.flash_attention.dense_attention)."""
    from ..ops.flash_attention import dense_attention
    return dense_attention(q, k, v, causal=causal)
