"""Mesh collectives layer — the ICI-native transport.

Where the reference moves bytes with epoll+TCP/ibverbs RDMA
(/root/reference/src/brpc/rdma/rdma_endpoint.h), a TPU pod moves tensors
over ICI via XLA collectives. This package is the transport those
capabilities map onto:

- fan-out (ParallelChannel)      → broadcast / all_gather over a mesh axis
- sharding (PartitionChannel)    → device_put with NamedSharding + all_to_all
- streaming windows              → ring ppermute schedules
- request/response over peers    → collective_permute pairs

Everything is jitted shard_map programs over a jax.sharding.Mesh — XLA
inserts the ICI DMA; we choose the schedule.
"""

from .mesh_transport import MeshTransport, default_mesh

__all__ = ["MeshTransport", "default_mesh"]
