"""Pipeline parallelism over a mesh axis.

The reference's "pipeline" is network-sense (pipelined connections,
SURVEY.md §2.9.5); model-stage pipelining is new TPU-first design: a
GPipe-style microbatch schedule expressed as one shard_map program —
stage parameters live stacked on the ``pp`` axis, activations hop to the
next stage via ppermute each tick, and the loop runs
``n_micro + n_stages - 1`` ticks (bubble included). XLA overlaps the
ppermute with the next tick's compute where the schedule allows.

Training (:func:`make_pipeline_train`): the conveyor is written as a
``lax.scan`` so reverse-mode AD is defined through it — differentiating
the forward conveyor yields the BACKWARD conveyor automatically (the
transpose of ``ppermute`` is the ppermute of the inverted ring, so
cotangents hop stage-to-stage in reverse order tick by tick), and the
scan's cotangent accumulation over ticks IS GPipe's microbatch gradient
accumulation.  One program, forward + backward, no hand-scheduled
bubbles; loss and grads match the unpipelined model exactly (same
arithmetic, reordered).
"""

from __future__ import annotations

from typing import Callable

from .mesh_transport import _shard_map


def make_pipeline(mesh, stage_fn: Callable, axis: str = "pp"):
    """Build f(stacked_params, microbatches) -> outputs.

    - ``stacked_params``: pytree whose leaves have leading dim
      ``n_stages`` (sharded over ``axis``) — stage i's slice feeds
      ``stage_fn`` on device i.
    - ``microbatches``: (n_micro, mb, ...) replicated; outputs
      (n_micro, mb, ...) replicated (read off the last stage).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def local(params, xs):
        # params leaves: (1, ...) per device; xs: (n_micro, mb, ...)
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        ticks = n_micro + n - 1
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)

        def body(t, carry):
            state, outputs = carry
            # stage 0 injects microbatch t (when one remains); others use
            # the activation that just arrived from the previous stage
            inject = xs[jnp.minimum(t, n_micro - 1)]
            inp = jnp.where(idx == 0, inject, state)
            out = stage_fn(my_params, inp)
            # ship to the next stage; the last stage's ppermute output to
            # stage 0 is ignored (overwritten by injection)
            state_next = jax.lax.ppermute(out, axis, fwd)
            # last stage emits the finished microbatch t-(n-1)
            done_idx = t - (n - 1)
            outputs = jax.lax.cond(
                jnp.logical_and(idx == n - 1, done_idx >= 0),
                lambda o: jax.lax.dynamic_update_slice(
                    o, out[None], (jnp.maximum(done_idx, 0),) +
                    (0,) * (o.ndim - 1)),
                lambda o: o,
                outputs)
            return (state_next, outputs)

        _, outputs = jax.lax.fori_loop(0, ticks, body, (state, outputs))
        # only the last stage holds real outputs: broadcast to all
        outputs = jax.lax.psum(
            jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)),
            axis)
        return outputs

    return jax.jit(_shard_map(jax)(
        local, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P()))


def make_pipeline_train(mesh, stage_fn: Callable, loss_fn: Callable,
                        axis: str = "pp", dp_axis: str = None):
    """Build ``step(stacked_params, xs, ys) -> (loss, grads)`` — a
    GPipe training step as ONE differentiated shard_map program.

    - ``stacked_params``: pytree, leaves with leading dim ``n_stages``
      (sharded over ``axis``); ``grads`` comes back in the same layout
      (each device holds exactly its stage's gradient slice).
    - ``xs``/``ys``: (n_micro, mb, ...) replicated microbatches/targets
      — or, with ``dp_axis`` set, sharded over that axis on the
      microbatch dim (each dp group runs the conveyor on its share and
      grads are pmean'd across dp: dp×pp composition in one program).
    - ``loss_fn(outputs, ys) -> scalar`` over all microbatches; the
      returned loss is the same scalar the unpipelined model produces.

    The forward conveyor is a ``lax.scan`` over
    ``n_micro + n_stages - 1`` ticks; reverse-mode AD through it runs
    the cotangent conveyor backwards (ppermute transposes to the
    inverted ring) and accumulates each stage's parameter cotangent
    across its microbatches — GPipe's backward schedule, derived rather
    than hand-written.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def local_loss(params, xs, ys):
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        ticks = n_micro + n - 1
        state0 = jnp.zeros_like(xs[0])
        outputs0 = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(idx == 0, inject, state)
            out = stage_fn(my_params, inp)
            state_next = jax.lax.ppermute(out, axis, fwd)
            done_idx = t - (n - 1)
            # emit on the last stage once the first microbatch has
            # traversed every stage; jnp.where keeps it differentiable
            emit = jnp.logical_and(idx == n - 1, done_idx >= 0)
            upd = jax.lax.dynamic_update_slice(
                outputs, out[None],
                (jnp.maximum(done_idx, 0),) + (0,) * (outputs.ndim - 1))
            outputs = jnp.where(emit, upd, outputs)
            return (state_next, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(ticks))
        # real outputs live on the last stage; replicate for the loss
        outputs = jax.lax.psum(
            jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)),
            axis)
        loss = loss_fn(outputs, ys)
        if dp_axis is not None:
            # dp×pp: each dp group pipelined its own batch share —
            # average the loss (AD's transpose of pmean then averages
            # the parameter cotangents across dp, i.e. data-parallel
            # gradient sync)
            loss = jax.lax.pmean(loss, dp_axis)
        return loss

    if dp_axis is None:
        in_specs = (P(axis), P(), P())
    else:
        # stage params sharded over pp (replicated across dp); the
        # microbatch dim of xs/ys sharded over dp
        in_specs = (P(axis), P(None, dp_axis), P(None, dp_axis))
    pipe_loss = _shard_map(jax)(
        local_loss, mesh=mesh,
        in_specs=in_specs,
        out_specs=P())

    return jax.jit(jax.value_and_grad(pipe_loss))
