"""Pipeline parallelism over a mesh axis.

The reference's "pipeline" is network-sense (pipelined connections,
SURVEY.md §2.9.5); model-stage pipelining is new TPU-first design: a
GPipe-style microbatch schedule expressed as one shard_map program —
stage parameters live stacked on the ``pp`` axis, activations hop to the
next stage via ppermute each tick, and the loop runs
``n_micro + n_stages - 1`` ticks (bubble included). XLA overlaps the
ppermute with the next tick's compute where the schedule allows.
"""

from __future__ import annotations

from typing import Callable

from .mesh_transport import _shard_map


def make_pipeline(mesh, stage_fn: Callable, axis: str = "pp"):
    """Build f(stacked_params, microbatches) -> outputs.

    - ``stacked_params``: pytree whose leaves have leading dim
      ``n_stages`` (sharded over ``axis``) — stage i's slice feeds
      ``stage_fn`` on device i.
    - ``microbatches``: (n_micro, mb, ...) replicated; outputs
      (n_micro, mb, ...) replicated (read off the last stage).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def local(params, xs):
        # params leaves: (1, ...) per device; xs: (n_micro, mb, ...)
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        ticks = n_micro + n - 1
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)

        def body(t, carry):
            state, outputs = carry
            # stage 0 injects microbatch t (when one remains); others use
            # the activation that just arrived from the previous stage
            inject = xs[jnp.minimum(t, n_micro - 1)]
            inp = jnp.where(idx == 0, inject, state)
            out = stage_fn(my_params, inp)
            # ship to the next stage; the last stage's ppermute output to
            # stage 0 is ignored (overwritten by injection)
            state_next = jax.lax.ppermute(out, axis, fwd)
            # last stage emits the finished microbatch t-(n-1)
            done_idx = t - (n - 1)
            outputs = jax.lax.cond(
                jnp.logical_and(idx == n - 1, done_idx >= 0),
                lambda o: jax.lax.dynamic_update_slice(
                    o, out[None], (jnp.maximum(done_idx, 0),) +
                    (0,) * (o.ndim - 1)),
                lambda o: o,
                outputs)
            return (state_next, outputs)

        _, outputs = jax.lax.fori_loop(0, ticks, body, (state, outputs))
        # only the last stage holds real outputs: broadcast to all
        outputs = jax.lax.psum(
            jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)),
            axis)
        return outputs

    return jax.jit(_shard_map(jax)(
        local, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P()))
