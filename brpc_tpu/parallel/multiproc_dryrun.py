"""Cross-PROCESS SPMD dry run: 2 ``jax.distributed`` processes × n/2
virtual CPU devices each, one global-mesh train step, one cross-process
ICI tensor transfer.

The single-process ``dryrun_multichip`` composes dp×tp×ep×sp×pp inside
one runtime; this module proves the same program model survives the
process boundary the way the reference's NCCL/MPI backend does
(SURVEY.md §5.8): the coordinator federates the per-process device sets
into one mesh, the train step's collectives cross the process boundary,
and an RPC carrying a device attachment moves a tensor between the two
interpreters (domains differ → the fabric's cross-process path, same
contract ``tests/test_ici_xfer.py`` pins).

Run as a module (one worker per process):

    python -m brpc_tpu.parallel.multiproc_dryrun <pid> <nproc> \
        <ndev_local> <coord_host:port> <rpc_port>

or drive both workers via :func:`run`, which ``__graft_entry__
.dryrun_multichip`` calls as its final stage (spawned with
``subprocess`` — ``multiprocessing`` spawn breaks under stdin-driven
parents, see bench.py's rationale).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time


def _worker(pid: int, nproc: int, ndev_local: int, coord: str,
            rpc_port: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    # REPLACE any inherited device-count flag (the single-process dry
    # run's parent exports 8; each worker must expose exactly its local
    # share or the federated mesh doubles up)
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={ndev_local}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    import jax

    try:
        # the axon sitecustomize pins JAX_PLATFORMS to the 1-chip TPU;
        # the dry run must stay on virtual CPU devices
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from brpc_tpu.models.embedding_ps import (PSConfig, batch_specs,
                                              init_params, param_specs,
                                              sgd_train_step)

    n_total = nproc * ndev_local
    devs = jax.devices()
    assert len(devs) == n_total, (len(devs), n_total)
    mesh = Mesh(np.array(devs).reshape(nproc, ndev_local), ("dp", "tp"))
    tp = ndev_local

    cfg = PSConfig(vocab=64 * tp, dim=32, slots=4, hidden=16 * tp,
                   classes=8, lr=0.1)
    # same PRNG on every process -> identical host values; each process
    # materializes only its addressable shards
    host_params = {k: np.asarray(v) for k, v in
                   init_params(jax.random.PRNGKey(0), cfg).items()}
    specs = param_specs(cfg)
    params = {
        k: jax.make_array_from_callback(
            host_params[k].shape, NamedSharding(mesh, specs[k]),
            lambda idx, a=host_params[k]: a[idx])
        for k in host_params}

    batch = 4 * nproc
    rng = np.random.default_rng(1)
    ids_h = rng.integers(0, cfg.vocab, (batch, cfg.slots), dtype=np.int32)
    lbl_h = rng.integers(0, cfg.classes, (batch,), dtype=np.int32)
    ids_spec, lbl_spec = batch_specs()
    ids = jax.make_array_from_callback(
        ids_h.shape, NamedSharding(mesh, ids_spec),
        lambda idx: ids_h[idx])
    labels = jax.make_array_from_callback(
        lbl_h.shape, NamedSharding(mesh, lbl_spec),
        lambda idx: lbl_h[idx])

    step = jax.jit(sgd_train_step, static_argnames=("lr",),
                   donate_argnums=(0,))
    with mesh:
        new_params, loss = step(params, ids, labels, lr=cfg.lr)
        jax.block_until_ready(loss)
    assert jnp.isfinite(loss), f"non-finite loss {loss}"
    emb_devs = {d.id for d in new_params["emb"].sharding.device_set}
    assert len(emb_devs) == n_total, (len(emb_devs), n_total)
    print(f"[p{pid}] cross-process SPMD train step ok: "
          f"loss={float(loss):.4f} over {n_total} devices "
          f"({nproc} processes)", flush=True)

    # barrier before the RPC stage so the server exists before the
    # client dials (a psum over the global mesh synchronizes processes)
    tok = jax.make_array_from_callback(
        (n_total,), NamedSharding(mesh, P(("dp", "tp"))),
        lambda idx: np.ones((n_total,), np.float32)[idx])
    sync = jax.jit(jnp.sum,
                   out_shardings=NamedSharding(mesh, P()))
    assert float(sync(tok)) == float(n_total)

    # cross-process ICI transfer: process 0 serves, process 1 sends a
    # device tensor as an RPC device attachment and checks the echo
    if pid == 0:
        from brpc_tpu.models.ps_service import PSService
        from brpc_tpu.server import Server

        srv = Server()
        srv.add_service(PSService(), name="PS")
        assert srv.start(f"127.0.0.1:{rpc_port}") == 0
        try:
            float(sync(tok))          # barrier: server is up, p1 may dial
            float(sync(tok))          # barrier: p1 finished its calls
        finally:
            srv.stop()
        print(f"[p{pid}] ici server stage done", flush=True)
    else:
        from brpc_tpu.client import Channel, Controller

        float(sync(tok))              # barrier: p0's server is up
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{rpc_port}") == 0, \
                "client channel init failed"
            x = jnp.arange(4096, dtype=jnp.float32)  # local device tensor
            got = None
            for attempt in range(10):
                cntl = Controller()
                cntl.timeout_ms = 30_000
                cntl.request_device_attachment = x
                c = ch.call_method("PS.EchoTensor", b"", cntl=cntl)
                if not c.failed \
                        and c.response_device_attachment is not None:
                    got = c.response_device_attachment.tensor()
                    break
                time.sleep(0.5)
            assert got is not None, \
                "cross-process tensor echo never succeeded"
            np.testing.assert_allclose(np.asarray(got), np.asarray(x))
            print(f"[p{pid}] cross-process ICI transfer ok "
                  f"({x.nbytes} bytes round-tripped)", flush=True)
        finally:
            # release p0's hold even on failure — a p1 error must
            # surface immediately, not after p0 burns the whole
            # parent timeout blocked in its barrier
            float(sync(tok))

    print(f"[p{pid}] 2-proc step ok", flush=True)


def run(n_devices: int = 8, processes: int = 2,
        timeout_s: float = 300.0) -> None:
    """Spawn the workers and raise unless every stage reports ok."""
    if n_devices % processes:
        raise ValueError(
            f"{n_devices} devices do not divide over {processes} "
            "processes")
    ndev_local = n_devices // processes
    # hold the probe sockets open until just before spawn: the port
    # must not be re-bindable by a stranger during the multi-second
    # worker startup window any longer than unavoidable
    probes = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        probes.append(s)
    coord_port, rpc_port = (s.getsockname()[1] for s in probes)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    import tempfile

    procs = []
    logs = []
    for s in probes:
        s.close()
    for pid in range(processes):
        # worker output goes to FILES: two workers coupled through
        # collectives + a parent draining pipes sequentially is a
        # deadlock (a chatty worker fills its 64KB pipe while the
        # parent blocks on its sibling)
        lf = tempfile.NamedTemporaryFile("w+", suffix=f".p{pid}.log",
                                         delete=False)
        logs.append(lf)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "brpc_tpu.parallel.multiproc_dryrun",
             str(pid), str(processes), str(ndev_local),
             f"127.0.0.1:{coord_port}", str(rpc_port)],
            cwd=repo, env=env, stdout=lf, stderr=subprocess.STDOUT))
    deadline = time.time() + timeout_s
    for p in procs:
        left = max(1.0, deadline - time.time())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
    outs = []
    for lf in logs:
        lf.flush()
        lf.seek(0)
        outs.append(lf.read())
        lf.close()
        os.unlink(lf.name)
    ok = all(p.returncode == 0 for p in procs) and all(
        "2-proc step ok" in o for o in outs)
    for i, o in enumerate(outs):
        for line in o.splitlines():
            if line.startswith("[p") or "Error" in line:
                print(line)
        if not ok and procs[i].returncode != 0:
            tail = "\n".join(o.splitlines()[-15:])
            print(f"--- worker {i} tail ---\n{tail}")
    if not ok:
        raise RuntimeError("multi-process dryrun failed")


if __name__ == "__main__":
    _worker(int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
            sys.argv[4], int(sys.argv[5]))
