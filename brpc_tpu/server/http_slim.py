"""Slim native HTTP dispatch — the Python half of the engine's kind-4
lane.

The round-6 slim tpu_std lane (`slim_dispatch.py`) proved that
per-message Python/GIL software overhead, not the wire, dominates
small-RPC throughput.  HTTP — the protocol browsers, load balancers
and the builtin portal actually speak — still paid that overhead once
per message: C++ cut the message (`EV_HTTP`), then Python parsed the
request line + headers (`protocol/http.py`), built an `HttpMessage`,
routed it, and sent each response through its own `engine.send`.

Kind 4 removes all of it from the eligible path: the C++ engine parses
the request line + headers itself, batches every eligible HTTP/1.1
request of a read burst, and enters Python ONCE calling the per-route
shim built below as ``handler(body, query, content_type, att_size,
conn_id, recv_ns, traceparent, deadline, tenant)`` (bytes-or-None for
the middle three, ``traceparent``, ``deadline`` and ``tenant`` — the
last is the raw ``x-tenant`` header, the fair-admission key;
``recv_ns`` is the
engine's CLOCK_MONOTONIC parse timestamp, used to backdate rpcz spans
so they cover native queueing).  ``traceparent`` is the raw W3C
trace-context header value the engine captured — explicitly traced
HTTP requests STAY on the slim lane, with the span parented to the
caller.  ``deadline`` is the raw ``x-deadline-ms`` header value (the
HTTP/1.1 spelling of tpu_std's remaining-deadline TLV 13): anchored
at ``recv_ns``, the shim SHEDS requests whose budget expired in the
native batch — 500 + ``x-rpc-error-code: ERPCTIMEDOUT``, handler
never runs (deadline plane).  The shim is the whole per-call Python
cost of the lane:

    admission   the SHARED overload-plane stage (server/admission.py):
                server cap, adaptive method cap, CoDel against the
                engine parse stamp, per-tenant fair admission — 503 +
                Retry-After answers ride the slim serializer,
                byte-identical with the classic ``build_response``
                output
    sampling    rpcz spans keep their per-second budget via
                start_server_span; traced requests always record and
                the slim lane records real sizes inline
    user code   entry.fn(cntl, request) with a REAL ServerController —
                handlers keep attachments, set_failed, begin_async,
                progressive attachments, session_local_data
    accounting  MethodStatus.on_responded with the measured latency

Return contract with the engine (flush_py_batch -> http_slim_item):

    (status, header_block, body)   serialized natively — status line +
                                   Content-Length + header_block +
                                   CRLF + body, coalesced into the
                                   burst's single writev.  The header
                                   block is pre-formatted "Name: v\\r\\n"
                                   lines, Content-Type first — exactly
                                   build_response's layout
    bytes                          a pre-serialized full response,
                                   appended verbatim (keeps wire order
                                   for classic-built edge responses)
    None                           completed (or will complete, for
                                   async/progressive methods) through
                                   the classic write path

Request-side ineligibility (chunked/`Expect`/`Upgrade` requests,
`Connection: close`, HTTP/1.0, unregistered paths, over-inbuf bodies)
never reaches the shim — the engine's header scan routes those
messages to the classic `EV_HTTP` path byte-identically.
"""

from __future__ import annotations

import json
import threading
from urllib.parse import unquote_plus

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..deadline import inherit_deadline
from ..protocol.http import build_response
from ..transport.socket import Socket
from .http_dispatch import _encode_http_body, http_status_for_error

_EREQUEST = int(Errno.EREQUEST)
_EINTERNAL = int(Errno.EINTERNAL)

_CT = b"Content-Type: "
_CRLF = b"\r\n"


def _hdr_block(ctype: str, extra) -> bytes:
    """The slim tuple's header block: Content-Type first, then extras —
    the exact line order build_response emits after Content-Length."""
    out = _CT + ctype.encode("latin1") + _CRLF
    if extra:
        for k, v in extra:
            out += f"{k}: {v}".encode("latin1") + _CRLF
    return out


def _query_to_json(query: bytes) -> bytes:
    """Mirror of HttpMessage.query() + the GET bridge's json.dumps."""
    out = {}
    for pair in query.decode("latin1").split("&"):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        out[unquote_plus(k)] = unquote_plus(v)
    return json.dumps(out).encode()


def make_http_slim_handler(bridge, server, entry, svc: str, mth: str,
                           http_method: str):
    """Build the kind-4 shim for one (service, method, HTTP-method)
    route.  All per-entry state is bound into closure cells — the
    steady-state call touches no module globals.

    The cross-cutting stages (admission → trace extract → deadline
    arm/shed, and the completion epilogue) live in the compiled
    interceptor chain — ``compile_http_slim_chain`` — the FOURTH chain
    binding of ROADMAP item 1.  The shim body keeps only what is
    lane-SPECIFIC: the inline-cell completion plumbing, request body /
    attachment / json2pb parsing, and the user-code call."""
    from .interceptors import compile_http_slim_chain

    fn = entry.fn
    req_type = entry.request_type
    full_name = entry.status.full_name
    socks = bridge._socks          # conn_id -> NativeSocket (live dict)
    is_get = http_method in ("GET", "HEAD")
    enter, settle = compile_http_slim_chain(server, entry, svc, mth,
                                            http_method)

    # ARITY CONTRACT (machine-checked): the engine's kind-4 call site
    # passes exactly these nine params — tools/check gates both sides
    # (the underscore defaults are chain bindings, not public params)
    def slim(body, query, ctype, attsz, conn_id, recv_ns,
             traceparent=None, deadline=None, tenant=None,
             _enter=enter, _settle=settle):
        sock = socks.get(conn_id)
        if sock is None:
            return None          # connection died mid-burst

        # Completion plumbing: while `inline` holds, the send closure
        # parks its response in `cell` and the engine serializes it into
        # the burst's coalesced writev; once the shim returns (async
        # methods), completions write classically via build_response —
        # same bytes, classic path.  The lock closes the race between a
        # fast async finisher and the shim's return.
        cell = []
        inline = [True]
        lk = threading.Lock()

        def _deliver(code, body_, ctype_, extra):
            with lk:
                if inline[0]:
                    cell.append((code, _hdr_block(ctype_, extra), body_))
                    return
            s = Socket.address(sock.id)
            if s is not None and not s.failed:
                # async completions land here AFTER the burst — a
                # drain may have started meanwhile: the late response
                # carries the x-lame-duck / Connection: close signal
                # exactly like the classic bridge's
                from .http_dispatch import drain_response_args
                extra, ka = drain_response_args(server, extra, True)
                s.write(build_response(code, body_, ctype_,
                                       headers=extra, keep_alive=ka))

        def send(cntl, response):
            # every response shape settles through the chain exactly
            # once (MethodStatus + limiter feed + span completion)
            if cntl.failed:
                if cntl._progressive is not None:
                    cntl._progressive._abort()
                code = http_status_for_error(cntl.error_code)
                body_ = cntl.error_text.encode()
                _settle(cntl, len(body_))
                _deliver(code, body_, "text/plain",
                         [("x-rpc-error-code", str(cntl.error_code))])
                return
            if cntl._progressive is not None:
                # chunked transfer: headers out now through the classic
                # writer (the chunk stream follows via Socket.write —
                # the engine's order guard staged earlier slim
                # responses first), byte-identical with _bridge_rpc
                body_, ctype_ = _encode_http_body(response)
                head = (b"HTTP/1.1 200 OK\r\n"
                        b"content-type: " + ctype_.encode() + b"\r\n"
                        b"transfer-encoding: chunked\r\n"
                        b"connection: keep-alive\r\n\r\n")
                first = (b"%x\r\n" % len(body_) + body_ + b"\r\n"
                         if body_ else b"")
                s = Socket.address(sock.id)
                if s is not None and not s.failed:
                    s.write(IOBuf(head + first))
                    cntl._progressive._start()
                _settle(cntl, len(body_))
                return
            body_, ctype_ = _encode_http_body(response)
            extra = None
            att = cntl.response_attachment.to_bytes() \
                if len(cntl.response_attachment) else b""
            if att:
                body_ += att
                extra = [("x-rpc-attachment-size", str(len(att)))]
            _settle(cntl, len(body_))
            _deliver(200, body_, ctype_, extra)

        # chain enter: admission → trace extract → deadline arm/shed.
        # A rejection comes back as the inline tuple; a shed already
        # completed through `send` and parked its tuple in the cell.
        cntl, early = _enter(len(body) if body is not None else 0,
                             sock.id, sock.remote_side, recv_ns, send,
                             traceparent, deadline, tenant)
        if cntl is None:
            if early is not None:
                return early
            return cell[0] if cell else None

        # request build — mirror of _bridge_rpc
        if is_get and query:
            request = _query_to_json(query)
        else:
            request = body
            asz = (attsz.decode("latin1").strip()
                   if attsz is not None else None)
            if asz and asz.isdigit():
                n = int(asz)
                if 0 < n <= len(request):
                    cntl.request_attachment = \
                        IOBuf(request[len(request) - n:])
                    request = request[:len(request) - n]
        try:
            from ..protocol.json2pb import maybe_parse_request
            ct = (ctype.decode("latin1").strip()
                  if ctype is not None else "")
            converted = maybe_parse_request(request, req_type, ct)
            if converted is not None:
                request = converted          # json2pb: JSON -> pb
            else:
                from ..protocol.tpu_std import parse_payload
                request = parse_payload(request, req_type)
        except Exception as e:
            cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
            cntl.finish(None)
            return cell[0] if cell else None
        try:
            with inherit_deadline(cntl):
                response = fn(cntl, request)
        except Exception as e:
            LOG.exception("http method %s raised", full_name)
            cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
            cntl.finish(None)
            return cell[0] if cell else None
        if cntl.is_async:
            with lk:
                inline[0] = False
                # a fast finisher may have completed before we returned
                return cell[0] if cell else None
        cntl.finish(response)
        with lk:
            inline[0] = False
            return cell[0] if cell else None

    return slim
