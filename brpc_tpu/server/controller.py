"""ServerController — per-request context handed to service methods.

The server half of the reference's Controller god-object
(/root/reference/src/brpc/controller.h:110): request meta, attachments in
both directions, error reporting, async completion, and the hooks the
dispatch layer uses to send the response exactly once.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from time import monotonic_ns as _mono_ns

from ..butil.endpoint import EndPoint
from ..butil.iobuf import IOBuf, LazyAttachmentsMixin
from ..butil.status import Errno
from ..protocol.meta import CompressType, RpcMeta


class ServerController(LazyAttachmentsMixin):
    __slots__ = (
        "request_meta", "remote_side", "socket_id",
        "_req_att", "_resp_att",
        "request_device_attachment", "response_device_attachment",
        "response_compress_type",
        "_error_code", "_error_text",
        "_async", "_finished", "_finish_lock", "_send_response",
        "begin_time_us", "trace_id", "span_id",
        "auth_context", "server",
        "_remote_stream_id", "_accepted_stream_id",
        "_accepted_stream_window", "span", "grpc_stream",
        "http_method", "http_path", "http_unresolved_path",
        "_session_data", "_progressive", "deadline_us",
        "_shm_handle", "_shm_extra", "_slim_fast",
    )

    def __init__(self, request_meta: RpcMeta,
                 remote_side: Optional[EndPoint],
                 socket_id: int,
                 send_response: Callable[["ServerController", Any], None]):
        self.request_meta = request_meta
        self.remote_side = remote_side
        self.socket_id = socket_id
        self._req_att: Optional[IOBuf] = None    # lazy (hot path)
        self._resp_att: Optional[IOBuf] = None   # lazy (hot path)
        # device tensors: in = DeviceAttachment handle (redeem with
        # .tensor()), out = a jax array to ship device-resident (ici/)
        self.request_device_attachment = None
        self.response_device_attachment = None
        self.response_compress_type = CompressType.NONE
        self._error_code = 0
        self._error_text = ""
        self._async = False
        self._finished = False
        self._finish_lock = threading.Lock()
        self._send_response = send_response
        self.begin_time_us = _mono_ns() // 1000
        self.trace_id = request_meta.trace_id
        self.span_id = request_meta.span_id
        self.auth_context: Any = None
        self.server: Any = None
        self._remote_stream_id = request_meta.stream_id
        self._accepted_stream_id = 0
        self._accepted_stream_window = 0
        self.span = None                 # rpcz Span when tracing is on
        self.grpc_stream = None          # GrpcServerStream on @grpc_streaming
        self.http_method = ""            # HTTP verb when bridged
        self.http_path = ""              # full request path when bridged
        self.http_unresolved_path = ""   # restful /* remainder
        self._session_data = None        # borrowed SimpleDataPool object
        self._progressive = None         # ProgressiveAttachment when used
        self._shm_handle = None          # request shm descriptor handle
        self._shm_extra = b""            # shm accept/offer TLVs to answer
        # trivial-shape slim fast item: admission counters were settled
        # per-burst, so completion feeds the recorders only (see
        # slim_dispatch's fast template + rpc_dispatch._send_response)
        self._slim_fast = False
        # absolute monotonic-µs deadline from the request's propagated
        # remaining budget (tpu_std TLV 13 / grpc-timeout / x-deadline-ms),
        # anchored at arrival; 0 = the request carries no deadline.  The
        # dispatch paths re-anchor it to the protocol parse timestamp
        # (deadline.arm) where one exists — construction time is the
        # LATEST possible arrival, so this default is conservative.
        tmo = request_meta.timeout_ms
        self.deadline_us = self.begin_time_us + tmo * 1000 if tmo > 0 else 0

    def reset_slim(self, remote_side, socket_id: int) -> None:
        """Reset-on-reuse for the slim lane's pooled controllers: every
        mutable slot back to its constructed state (``request_meta``,
        ``_send_response`` and ``_finish_lock`` are per-entry constants
        the pool preserves; the meta's own reset is the caller's job).
        NO state — attachments, errors, deadline, spans, session data,
        shm handles — survives into the next request (pinned by
        tests/test_client_lane.py)."""
        self.remote_side = remote_side
        self.socket_id = socket_id
        self._req_att = None
        self._resp_att = None
        self.request_device_attachment = None
        self.response_device_attachment = None
        self.response_compress_type = CompressType.NONE
        self._error_code = 0
        self._error_text = ""
        self._async = False
        self._finished = False
        self.begin_time_us = 0
        self.trace_id = 0
        self.span_id = 0
        self.auth_context = None
        self._remote_stream_id = 0
        self._accepted_stream_id = 0
        self._accepted_stream_window = 0
        self.span = None
        self.grpc_stream = None
        self.http_method = ""
        self.http_path = ""
        self.http_unresolved_path = ""
        self._session_data = None
        self._progressive = None
        self.deadline_us = 0
        self._shm_handle = None
        self._shm_extra = b""
        self._slim_fast = False

    # -- deadline plane ----------------------------------------------------

    def deadline_remaining_ms(self) -> Optional[float]:
        """Remaining budget of THIS request's propagated deadline in
        milliseconds (negative once expired), or None when the request
        carries no deadline.  Handlers doing expensive work should check
        it between stages and give downstream calls no more than this
        (downstream calls issued on the handler's own call stack inherit
        it automatically — see brpc_tpu.deadline.inherit_deadline)."""
        if not self.deadline_us:
            return None
        return (self.deadline_us - _mono_ns() // 1000) / 1000.0

    @property
    def deadline_expired(self) -> bool:
        """True when the request's propagated deadline has passed — the
        caller has given up; any further work is doomed."""
        return bool(self.deadline_us) \
            and _mono_ns() // 1000 >= self.deadline_us

    # -- error reporting ---------------------------------------------------

    @property
    def failed(self) -> bool:
        return self._error_code != 0

    def set_failed(self, code_or_text, text: str = "") -> None:
        """``cntl.set_failed("oops")`` or ``cntl.set_failed(EREQUEST, "x")``."""
        if isinstance(code_or_text, str):
            self._error_code = int(Errno.EINTERNAL)
            self._error_text = code_or_text
        else:
            self._error_code = int(code_or_text)
            self._error_text = text

    @property
    def error_code(self) -> int:
        return self._error_code

    @property
    def error_text(self) -> str:
        return self._error_text

    # -- async completion --------------------------------------------------

    def begin_async(self) -> None:
        """Declare that the response will be sent later via
        :meth:`finish` (≈ brpc's done->Run() ownership transfer)."""
        self._async = True

    @property
    def is_async(self) -> bool:
        return self._async

    def finish(self, response: Any = None) -> None:
        """Send the response for an async method. Idempotent — the first
        call wins (mirrors SendRpcResponse's done-once guard)."""
        with self._finish_lock:
            if self._finished:
                return
            self._finished = True
        self._send_response(self, response)
        if self._session_data is not None and self.server is not None \
                and self.server._session_pool is not None:
            self.server._session_pool.give_back(self._session_data)
            self._session_data = None

    def session_local_data(self) -> Any:
        """Reusable per-request user data from the server's
        SimpleDataPool (≈ Controller::session_local_data); None when the
        server has no session_local_data_factory."""
        if self._session_data is None and self.server is not None \
                and self.server._session_pool is not None:
            self._session_data = self.server._session_pool.borrow()
        return self._session_data

    def create_progressive_attachment(self):
        """HTTP-bridged methods only: switch the response to chunked
        transfer and return a ProgressiveAttachment the handler (or a
        background task) writes to after returning
        (≈ src/brpc/progressive_attachment.h)."""
        from .http_dispatch import ProgressiveAttachment
        if self._progressive is None:
            self._progressive = ProgressiveAttachment(self.socket_id)
        return self._progressive

    def annotate(self, text: str) -> None:
        """Add a note to the request's rpcz span (no-op when tracing is
        off) — ≈ TRACEPRINTF into the current span."""
        if self.span is not None:
            self.span.annotate(text)

    def _mark_finished_if_first(self) -> bool:
        with self._finish_lock:
            if self._finished:
                return False
            self._finished = True
            return True
