"""Kind-5 streaming lane — the Python half of the engine's native
stream transport.

Two entries, both called from engine loop threads inside the per-burst
batched GIL entry:

``make_stream_handler`` builds the STREAM-OPEN shim for one kind-3
method: the engine scans the stream TLVs (12/14) out of an eligible
unary request and dispatches here instead of the kind-3 shim, as
``handler(payload, att, cid, conn_id, dom, nonce, recv_ns, trace,
timeout_ms, tenant, stream_id, stream_window)``.  Unlike the six
hand-replicated lane bodies before it, this lane BINDS the compiled
interceptor chain (server/interceptors.py — admission → deadline shed
→ trace extract → MethodStatus/rpcz → telemetry): the body calls
``enter`` before user code and ``settle`` after, and cannot reorder or
drop a stage (the lane linter pins the binding mechanically).  On
success the accepted stream is REGISTERED with the engine before the
grant response leaves — write-side credit is then accounted in C++
(``Stream.write`` routes through ``engine.stream_write``), and the
response frame carries the grant TLVs natively.

``slim_chunks`` is the batched chunk delivery: ALL DATA/CLOSE chunks
of a read burst — across every stream on the loop — enter Python in
this ONE call (the kind-3/4 discipline applied to stream frames;
credit FEEDBACK frames never enter Python at all, the engine settles
them in C++).  Chunks route into the existing ``Stream.on_frame``
machinery, so ordering, ack generation and close semantics are
identical with the Python lane by construction.

Return contract of the open shim with the engine (stream_open_item):

    (payload, grant_bytes)   success with an accepted stream: the
                             pre-encoded grant TLVs (stream id +
                             window) ride the response meta natively
    bytes / memoryview       success, method declined the stream
    None                     escalated through the classic completion
                             (async, errors, compressed/device/
                             attachment responses) — byte-identical
"""

from __future__ import annotations

import struct as _struct

from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..deadline import inherit_deadline
from ..protocol.meta import TAG_STREAM_ID, TAG_STREAM_WINDOW, encode_tlv
from ..protocol.tpu_std import parse_payload
from ..streaming import find_stream
from .interceptors import compile_chain

# Closed kind-5 fallback reason-name mirror — MUST match engine.cpp's
# kStreamFbNames order exactly (tools/check gates it in tier-1).  The
# bridge pre-seeds the fallback family with these so every reason row
# exists from the first scrape.
STREAM_FB_NAMES = (
    "stream_no_shim", "stream_non_inline", "stream_compressed",
    "stream_chunk_oversize", "stream_drain", "stream_unregistered",
)


def make_stream_handler(bridge, server, entry, svc: str, mth: str):
    """Build the kind-5 stream-open shim for one (service, method)
    entry.  All per-entry state is bound into default args; the
    cross-cutting stages come from the compiled interceptor chain."""
    enter, settle = compile_chain(server, entry, "stream")
    engine = bridge.engine

    # ARITY CONTRACT (machine-checked): the engine's kind-5 call site
    # passes exactly the public params below — tools/check gates both
    # sides (privates are the underscore-prefixed default binds)
    def slim(payload, att, cid, conn_id, dom, nonce, recv_ns,
             trace=None, tmo=None, tenant=None, stream_id=0,
             stream_window=0,
             _enter=enter, _settle=settle, _fn=entry.fn,
             _rt=entry.request_type, _socks=bridge._socks,
             _engine=engine, _inherit=inherit_deadline,
             _find=find_stream, _pack=_struct.pack,
             _tlv=encode_tlv):
        sock = _socks.get(conn_id)
        if sock is None:
            return None          # connection died mid-burst
        # ---- the interceptor-chain binding: admission → shed → trace
        # run INSIDE enter, in pinned order — a None return means the
        # client is already answered (rejection / shed) and every
        # taken count is settled
        cntl = _enter(sock, cid, len(payload), att, dom, nonce,
                      recv_ns, trace, tmo, tenant)
        if cntl is None:
            return None
        cntl._remote_stream_id = stream_id
        cntl.request_meta.stream_id = stream_id
        cntl.request_meta.stream_window = stream_window
        try:
            request = parse_payload(payload, _rt)
        except Exception as e:
            cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
            cntl.finish(None)
            return None
        try:
            with _inherit(cntl):
                response = _fn(cntl, request)
        except Exception as e:
            LOG.exception("method %s raised",
                          cntl.request_meta.service_name)
            cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
            cntl.finish(None)
            return None
        if cntl.is_async:
            return None          # user owns completion via cntl.finish
        ratt = cntl._resp_att
        if (cntl.failed or cntl.response_compress_type
                or cntl.response_device_attachment is not None
                or (ratt is not None and len(ratt))
                or not isinstance(response,
                                  (bytes, bytearray, memoryview))):
            # anything the native grant frame cannot express: classic
            # completion — byte-identical by construction (the classic
            # meta carries the grant TLVs for accepted streams)
            cntl.finish(response)
            return None
        if not cntl._mark_finished_if_first():
            # lost the finish race (the deadline kicker already sent
            # an error frame — no grant ever reaches the client): the
            # stream must NOT be adopted, or the engine would keep a
            # live session the peer will never bind
            return None
        grant = None
        acc = cntl._accepted_stream_id
        if acc:
            # grant TLVs ride the response meta natively; the stream is
            # adopted onto the kind-5 lane BEFORE the response leaves,
            # so no peer frame can race the registration
            grant = (_tlv(TAG_STREAM_ID, _pack("<Q", acc))
                     + _tlv(TAG_STREAM_WINDOW,
                            _pack("<I", cntl._accepted_stream_window)))
            s = _find(acc)
            if s is not None:
                _engine.stream_register(conn_id, acc, stream_id,
                                        s._write_window)
                s._native_tx = _engine
        # ---- chain epilogue: MethodStatus/limiter feed + span finish
        _settle(cntl, len(response))
        if grant is not None:
            return response, grant
        return response

    return slim


def slim_chunks(items) -> None:
    """Batched kind-5 chunk delivery — ONE GIL entry per read burst
    covering every stream on the loop.  Routes into the existing
    ``Stream.on_frame`` machinery (per-stream ExecutionQueue ordering,
    consumption-driven acks, ordered close), so delivery semantics are
    identical with the Python lane.  The engine only batches frames
    whose (sid, conn) binding matched its registration — the forged-
    frame guard ran in C++."""
    find = find_stream
    for sid, flags, payload in items:
        s = find(sid)
        if s is None:
            continue             # closed since the frame was cut
        try:
            s.on_frame(flags, payload)
        except Exception:
            LOG.exception("stream chunk delivery raised (sid=%d)", sid)
