"""Server side — service registry, request dispatch, lifecycle.

Capability parity with /root/reference/src/brpc/server.h:409-451 (Server::
AddService/Start/Stop/Join, ServerOptions) re-designed for the TPU stack:
the request path runs on fiber tasks, every method gets latency/qps/
concurrency bvars, and the builtin observability portal mounts on the
same port via the multi-protocol messenger.
"""

from .server import Server, ServerOptions
from .service import Service, grpc_streaming, method
from .controller import ServerController

__all__ = ["Server", "ServerOptions", "Service", "ServerController",
           "method", "grpc_streaming"]
