"""Hot restart — listener fd passing over a unix socket.

The second half of the operability plane (the first is graceful drain,
server.py): a binary swap must not drop the kernel listen queue or
refuse a single connect.  The OLD process exports its bound listening
sockets over a unix domain socket (``SCM_RIGHTS`` — the nginx
``USR2``/fd-inheritance discipline, done explicitly so the successor
can be a freshly exec'd binary rather than a fork child); the NEW
process imports them and serves from the SAME kernel sockets:
connections sitting in the listen queue during the swap are accepted
by the successor as if nothing happened.

Two mechanisms compose for zero-failed-request restarts:

1. **SO_REUSEPORT overlap start** — with the round-15 sharded
   listeners (or ``ServerOptions.reuse_port``) the successor may
   simply bind the same port while the predecessor drains: the kernel
   splits new accepts between them, and the predecessor's lame-duck
   signal steers clients over.
2. **fd passing (this module)** — exact listen-queue preservation:
   the predecessor's fds (primary + SO_REUSEPORT shards) move to the
   successor; the predecessor then drains its ESTABLISHED connections
   to completion and exits.

Wire shape on the handoff socket: ``b"TPUHR1" + u32 meta_len + meta``
(JSON: the per-fd ``(host, port)`` list) with every fd in one
``SCM_RIGHTS`` ancillary block on the first sendmsg.
"""

from __future__ import annotations

import array
import json
import os
import socket
import struct
from typing import List, Optional, Tuple

from ..butil.logging_util import LOG

MAGIC = b"TPUHR1"
_MAX_FDS = 64


def send_listener_fds(conn: socket.socket, socks: List) -> None:
    """Ship ``socks``' fds (+ their bound addresses as metadata) over
    an accepted handoff connection."""
    addrs = []
    for s in socks:
        name = s.getsockname()
        addrs.append([name[0], name[1]])
    meta = json.dumps({"addrs": addrs}).encode()
    fds = array.array("i", [s.fileno() for s in socks])
    conn.sendmsg([MAGIC + struct.pack("<I", len(meta)) + meta],
                 [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                   fds.tobytes())])


def recv_listener_fds(conn: socket.socket
                      ) -> List[Tuple[socket.socket, str, int]]:
    """Receive the handoff: returns ``[(sock, host, port), ...]`` —
    each ``sock`` is a live ``socket.socket`` wrapping an inherited,
    already-bound-and-listening fd."""
    fds: List[int] = []
    # ancillary data rides the FIRST datagram of the stream
    data, ancdata, _flags, _addr = conn.recvmsg(
        65536, socket.CMSG_LEN(_MAX_FDS * 4))
    for cmsg_level, cmsg_type, cmsg_data in ancdata:
        if cmsg_level == socket.SOL_SOCKET \
                and cmsg_type == socket.SCM_RIGHTS:
            arr = array.array("i")
            arr.frombytes(cmsg_data[:len(cmsg_data)
                                    - len(cmsg_data) % 4])
            fds.extend(arr)
    if not data.startswith(MAGIC) or len(data) < len(MAGIC) + 4:
        for fd in fds:
            os.close(fd)
        raise ValueError("bad hot-restart handoff header")
    (mlen,) = struct.unpack_from("<I", data, len(MAGIC))
    body = data[len(MAGIC) + 4:]
    while len(body) < mlen:
        chunk = conn.recv(65536)  # bounded by settimeout  # static-check: allow
        if not chunk:
            break
        body += chunk
    try:
        meta = json.loads(body[:mlen].decode())
        addrs = meta["addrs"]
    except (ValueError, KeyError):
        for fd in fds:
            os.close(fd)
        raise ValueError("bad hot-restart handoff metadata") from None
    if len(addrs) != len(fds):
        for fd in fds:
            os.close(fd)
        raise ValueError(
            f"hot-restart handoff mismatch: {len(addrs)} addrs vs "
            f"{len(fds)} fds")
    out = []
    for fd, (host, port) in zip(fds, addrs):
        out.append((socket.socket(fileno=fd), host, int(port)))
    return out


def handoff_listeners(path: str, socks: List,
                      timeout_s: float = 30.0) -> int:
    """Predecessor side: serve ONE handoff request at unix-socket
    ``path`` (bounded by ``timeout_s``), shipping every listener fd to
    whoever connects.  Returns 0 on success, -1 on timeout/error.
    Typically run on its own thread while the server keeps serving;
    afterwards the caller drains and stops."""
    if not socks:
        return -1
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        srv.bind(path)
        srv.listen(1)
        srv.settimeout(timeout_s)
        conn, _ = srv.accept()    # bounded by settimeout  # static-check: allow
        try:
            conn.settimeout(timeout_s)
            send_listener_fds(conn, socks)
        finally:
            conn.close()
        return 0
    except (OSError, socket.timeout) as e:
        LOG.warning("hot-restart handoff at %s failed: %s", path, e)
        return -1
    finally:
        srv.close()
        try:
            os.unlink(path)
        except OSError:
            pass


def import_listeners(path: str, timeout_s: float = 10.0
                     ) -> List[Tuple[socket.socket, str, int]]:
    """Successor side: connect to the predecessor's handoff socket and
    take over its listeners.  Raises OSError/ValueError on failure —
    the caller decides whether to fall back to a fresh bind."""
    cli = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    cli.settimeout(timeout_s)
    try:
        cli.connect(path)         # bounded by settimeout  # static-check: allow
        return recv_listener_fds(cli)
    finally:
        cli.close()
