"""Builtin observability portal — HTTP pages on the serving port.

≈ /root/reference/src/brpc/builtin/ (25 services, server.cpp:464-559):
status, vars, flags (live-set with validator gate), health, connections,
version, prometheus metrics, runtime introspection (sockets/fibers/ids),
and the service index. Handlers return
(status, content_type, body, extra_headers).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...butil import flags as flags_mod
from ...bvar.prometheus import render_prometheus
from ...bvar.variable import dump_exposed, find_exposed, list_exposed
from ...protocol.http import HttpMessage

Handler = Callable[[object, HttpMessage, List[str]], Tuple]

_routes: Dict[str, Handler] = {}

_START_TIME = time.time()


def register_builtin(prefix: str, handler: Handler) -> None:
    """Register a portal page.  Re-registering a prefix with a
    DIFFERENT handler is almost always an import-order accident (two
    modules claiming one page — the /rpcz JSON contract broke this way
    once): the newest registration wins, loudly, so the shadowed page
    is discoverable instead of silently serving the wrong handler."""
    existing = _routes.get(prefix)
    if existing is not None and existing is not handler:
        from ...butil.logging_util import LOG
        LOG.warning("builtin page %r re-registered: %s replaces %s",
                    prefix or "/", getattr(handler, "__name__", handler),
                    getattr(existing, "__name__", existing))
    _routes[prefix] = handler


def route_builtin(server, msg: HttpMessage):
    parts = [p for p in msg.path.split("/") if p]
    head = parts[0] if parts else ""
    handler = _routes.get(head)
    if handler is None:
        return 404, "text/plain", f"no such page: {msg.path}\n".encode(), []
    out = handler(server, msg, parts[1:])
    if len(out) == 3:
        status, ctype, body = out
        extra: List = []
    else:
        status, ctype, body, extra = out
    if isinstance(body, str):
        body = body.encode()
    return status, ctype, body, extra


# ---- pages ---------------------------------------------------------------

def _index(server, msg, rest):
    lines = ["tpu-rpc server", "=" * 40, "", "services:"]
    for (svc, mth), entry in sorted(server.methods.items()):
        lines.append(f"  /{svc}/{mth}")
    lines += ["", "builtin pages:"]
    for p in sorted(_routes):
        if p:
            lines.append(f"  /{p}")
    return 200, "text/plain", "\n".join(lines) + "\n"


def _health(server, msg, rest):
    # drain-state observability: a load balancer polling /health sees
    # 503 + x-lame-duck the moment drain starts and takes the node out
    # of rotation — kubernetes-readiness-probe shaped (the header rides
    # even with enable_lame_duck off; the health poll IS the poll-based
    # spelling of the signal)
    if getattr(server, "draining", False):
        return 503, "text/plain", "draining\n", [("x-lame-duck", "1")]
    return 200, "text/plain", "OK\n"


def _version(server, msg, rest):
    from ... import __version__
    return 200, "text/plain", f"tpu-rpc/{__version__} {server.version}\n"


def _status(server, msg, rest):
    from ...fiber.runtime import global_runtime

    rt = global_runtime()
    out = {
        "uptime_s": round(time.time() - _START_TIME, 1),
        "listen": str(server.listen_endpoint),
        "connections": server.connection_count(),
        "inflight_requests": server.inflight,
        "fiber_workers": rt.worker_count,
        "fiber_pending": rt.pending_count,
        # operability plane: drain phase + what the drain still waits
        # for (the rolling-restart operator's watch keys)
        "drain_phase": getattr(server, "drain_phase", "serving"),
        "drain_inflight_remaining": server.inflight
        if getattr(server, "draining", False) else 0,
        "drain_force_closed": getattr(server, "drain_force_closed", 0),
        "services": {},
    }
    for (svc, mth), entry in sorted(server.methods.items()):
        st = entry.status
        out["services"][f"{svc}.{mth}"] = {
            "count": st.latency.count(),
            "qps": round(st.latency.qps(), 1),
            "latency_us_p50": round(st.latency.p50(), 1),
            "latency_us_p99": round(st.latency.p99(), 1),
            "errors": st.errors.get_value(),
            "inflight": st.inflight,
            # the limit admission actually enforces: an installed
            # adaptive limiter's LIVE value (a static 0 next to an
            # AutoLimiter used to read as "unlimited")
            "max_concurrency": st.live_max_concurrency(),
            "concurrency_limiter": st.limiter_kind(),
        }
    return 200, "application/json", json.dumps(out, indent=1)


def _vars(server, msg, rest):
    q = msg.query()
    if "expand" in q:
        # live trend graph (≈ the reference portal's flot charts): the
        # first request starts 1Hz recording; refreshes show the curve
        from ...bvar.trend import render_sparkline_svg, track
        name = q["expand"]
        t = track(name)
        if t is None:
            return 404, "text/plain", f"no var {name}\n"
        v = find_exposed(name)
        svg = render_sparkline_svg(list(t.ring))
        return (200, "text/html",
                f"<html><body style='font:13px monospace'>"
                f"<h3>{name} = {v.describe()}</h3>{svg}"
                f"<p><a href=''>refresh</a> · <a href='/vars'>all vars"
                f"</a></p></body></html>")
    if rest:
        v = find_exposed(rest[0])
        if v is None:
            return 404, "text/plain", f"no var {rest[0]}\n"
        return 200, "text/plain", f"{rest[0]} : {v.describe()}\n"
    filt = q.get("filter", "")
    dump = dump_exposed(filt)
    body = "".join(f"{k} : {v}\n" for k, v in sorted(dump.items()))
    return 200, "text/plain", body


def _metrics(server, msg, rest):
    if msg.query().get("fleet") == "1":
        # federation view: every live member's families merged under
        # an instance label (registry hosts only; one scrape sweep per
        # interval — the cache inside federate())
        from ... import fleet as fleet_mod
        reg = fleet_mod.registry_of(server)
        if reg is None:
            return 404, "text/plain", "no fleet registry on this server\n"
        return 200, "text/plain; version=0.0.4", reg.federate()
    return 200, "text/plain; version=0.0.4", render_prometheus()


def _flags(server, msg, rest):
    q = msg.query()
    if rest:
        f = next((x for x in flags_mod.list_flags() if x.name == rest[0]),
                 None)
        if f is None:
            return 404, "text/plain", f"no flag {rest[0]}\n"
        if "setvalue" in q:
            if not flags_mod.set_flag(f.name, q["setvalue"]):
                return 403, "text/plain", \
                    f"flag {f.name} is not settable to {q['setvalue']!r}\n"
            return 200, "text/plain", \
                f"{f.name} set to {f.value!r}\n"
        return 200, "text/plain", _flag_line(f)
    body = "".join(_flag_line(f) for f in flags_mod.list_flags())
    return 200, "text/plain", body


def _flag_line(f) -> str:
    mark = " (R)" if f.reloadable else ""
    return f"{f.name}={f.value!r} default={f.default!r}{mark}  # {f.help}\n"


def _connections(server, msg, rest):
    from ...transport.socket import socket_pool

    out = {
        "server_connections": server.connection_count(),
        "socket_slots": len(socket_pool()),
    }
    return 200, "application/json", json.dumps(out, indent=1)


def _fibers(server, msg, rest):
    from ...fiber.runtime import global_runtime

    rt = global_runtime()
    return 200, "application/json", json.dumps({
        "workers": rt.worker_count,
        "pending": rt.pending_count,
        "concurrency": rt.concurrency,
    }, indent=1)


def _list_vars(server, msg, rest):
    return 200, "application/json", json.dumps(list_exposed())


def _rpcz(server, msg, rest):
    """/rpcz — span browser + distributed trace queries.

    Query modes:
      (none)                       recent local spans (JSON)
      ?trace_id=HEX&format=json    this process's spans of one trace —
                                   the stitcher's per-hop fetch; always
                                   bounded by &limit (never the full
                                   store in one response)
      ?trace_id=HEX&stitch=1       follow client spans' remote_side
                                   over RPC and merge the sub-process
                                   spans (clock skew annotated); render
                                   as JSON (+ nested tree), as
                                   format=chrome (Perfetto-loadable
                                   Chrome trace events), or as
                                   format=tree (text tree)
      ?start_us=&end_us=&persisted=1   sqlite time-range browse (dead
                                   ranks included), paged by &limit and
                                   the start_us/end_us cursor
    """
    from ...rpcz import (browse_persisted, global_span_store,
                         rpcz_enabled)

    store = global_span_store()
    q = msg.query()
    try:
        limit = max(1, int(q.get("limit", "100")))
    except ValueError:
        return 400, "text/plain", "bad limit (integer)\n"
    fmt = q.get("format", "json")
    tid = 0
    if "trace_id" in q:
        try:
            tid = int(q["trace_id"], 16)
        except ValueError:
            return 400, "text/plain", "bad trace_id (hex)\n"
    if "start_us" in q or "end_us" in q or "persisted" in q:
        # time-range browse over the sqlite mirrors (rpcz_dir) — covers
        # spans of DEAD processes too (≈ the reference's leveldb-backed
        # time browsing, span.cpp:306-319).  ``limit`` + the
        # start_us/end_us cursor page the 200K-row mirror; a stitcher
        # (or any scraper) can never pull the whole db in one response.
        try:
            start_us = int(q.get("start_us", "0"))
            end_us = int(q.get("end_us", "0"))
        except ValueError:
            return 400, "text/plain", "bad start_us/end_us (integer)\n"
        store.flush_now()          # what's pending is browsable now
        return 200, "application/json", json.dumps({
            "enabled": rpcz_enabled(),
            "persisted": True,
            "spans": browse_persisted(start_us, end_us, limit, tid),
        }, indent=1)
    if tid:
        from ...rpcz_stitch import (annotate_skew, build_tree,
                                    render_tree_text, to_chrome_trace)
        if "stitch" in q:
            from ...rpcz_stitch import collect_trace
            try:
                hops = max(1, int(q.get("max_hops", "16")))
                budget_s = float(q.get("budget_s", "8"))
            except ValueError:
                return (400, "text/plain",
                        "bad max_hops (integer) / budget_s (number)\n")
            stitched = collect_trace(
                tid, limit=limit, max_hops=hops, budget_s=budget_s,
                # never RPC ourselves: our spans ARE the local seed
                skip=(str(server.listen_endpoint),))
            spans = stitched["spans"]
            extra = {"stitched": True, "remotes": stitched["remotes"],
                     "truncated": stitched["truncated"]}
        else:
            spans = [s.describe() for s in store.by_trace(tid, limit)]
            for s in spans:
                s["source"] = "local"
            annotate_skew(spans)
            extra = {"stitched": False}
        if fmt == "chrome":
            return (200, "application/json",
                    json.dumps(to_chrome_trace(spans)))
        if fmt == "tree":
            return (200, "text/plain",
                    f"trace {tid:x} — " + render_tree_text(spans))
        out = {"enabled": rpcz_enabled(), "trace_id": f"{tid:x}",
               "spans": spans, "tree": build_tree(spans)}
        out.update(extra)
        return 200, "application/json", json.dumps(out, indent=1)
    spans = store.recent(limit)
    return 200, "application/json", json.dumps({
        "enabled": rpcz_enabled(),
        "spans": [s.describe() for s in reversed(spans)],
    }, indent=1)


def _hist_view(buckets, count, total) -> Dict:
    """Portal rendering of one engine histogram: non-empty buckets
    keyed by exclusive upper bound, plus count/avg."""
    from ...transport.native_bridge import bucket_label
    view = {bucket_label(i, len(buckets)): n
            for i, n in enumerate(buckets) if n}
    return {
        "count": count,
        "avg": round(total / count, 1) if count else 0,
        "buckets": view,
    }


def _native(server, msg, rest):
    """/native — the native engine's always-on telemetry table: per-lane
    stage histograms (queue = frame parse -> batched shim entry, shim =
    dispatch time, resid = parse -> response build), burst/writev
    coalescing distributions, reason-coded fallback counters with the
    top reasons per route/method, loop busy ratios and high-water
    marks.  One engine.telemetry() snapshot renders the whole page."""
    bridge = getattr(server, "_native_bridge", None)
    if bridge is None:
        return (404, "text/plain",
                "this server has no native engine (ServerOptions.native"
                " is off)\n")
    # the shared cache: a hot dashboard polling /native costs one
    # engine snapshot per TTL, same as the bvar readers
    t = bridge.telemetry.get()
    lanes = {}
    for ln, d in t["lanes"].items():
        lanes[ln] = {
            "handled": d["handled"],
            "errors": d["errors"],
            "queue_us": _hist_view(d["queue_us"], d["queue_us_count"],
                                   d["queue_us_sum"]),
            "shim_us": _hist_view(d["shim_us"], d["shim_us_count"],
                                  d["shim_us_sum"]),
            "resid_us": _hist_view(d["resid_us"], d["resid_us_count"],
                                   d["resid_us_sum"]),
        }
    top_fallbacks = sorted(
        ((k, v) for k, v in t["fallbacks"].items() if v),
        key=lambda kv: -kv[1])

    def _per_target(table):
        out = {}
        for name, d in sorted(table.items()):
            fbs = sorted(((k[3:], v) for k, v in d.items()
                          if k.startswith("fb_") and v),
                         key=lambda kv: -kv[1])
            row = {"handled": d["handled"], "errors": d["errors"]}
            if fbs:
                row["top_fallbacks"] = dict(fbs)
            out[name] = row
        return out

    # per-loop view: lifetime busy ratio plus the multi-core engine's
    # placement counters (accepts = conns pinned by this loop, frames =
    # messages it parsed, handoffs = cross-loop completion nodes it
    # consumed, spin_polls = busy-poll harvests).  The WINDOWED per-
    # loop ratios and their max−min spread come from the shared cache —
    # the aggregate busy ratio masks exactly the imbalance these show.
    windowed = bridge.telemetry.per_loop_busy_ratios()
    loops = []
    for i, lo in enumerate(t["loops"]):
        denom = lo["busy_ns"] + lo["idle_ns"]
        loops.append({
            "busy_ratio": round(lo["busy_ns"] / denom, 4) if denom
            else 0.0,
            "busy_ratio_windowed": round(windowed[i], 4)
            if i < len(windowed) else 0.0,
            "busy_ms": round(lo["busy_ns"] / 1e6, 1),
            "idle_ms": round(lo["idle_ns"] / 1e6, 1),
            "polls": lo["polls"],
            "spin_polls": lo.get("spin_polls", 0),
            "accepts": lo.get("accepts", 0),
            "frames": lo.get("frames", 0),
            "handoffs": lo.get("handoffs", 0),
        })
    from ...client.fast_call import scatter_fallback_counters
    from ...deadline import shed_counters
    from ...transport.client_lane import client_lane_telemetry
    # CLIENT LANE section: this process's native response demux
    # (process-global — any channel in this process may ride it).
    # completions vs reason-coded fallbacks plus the completions-per-
    # burst histogram; empty when no socket ever attached.
    cl = client_lane_telemetry()
    client_lane = {}
    if cl:
        client_lane = {
            "completions": cl.get("completions", 0),
            "fallback_total": cl.get("fallback_total", 0),
            "fallbacks": {k: v for k, v in cl.get("fallbacks",
                                                  {}).items() if v},
            "bursts": cl.get("bursts", 0),
            "attached": cl.get("attached", 0),
            "acks": cl.get("acks", 0),
            "demux_loops": cl.get("demux_loops", 1),
            "loops": cl.get("loops", []),
            "completions_per_burst": _hist_view(
                cl["comp_burst"], cl["comp_burst_count"],
                cl["comp_burst_sum"]),
        }
    # STREAMING section (kind-5 lane): streams open, chunk flow both
    # directions, the chunks-per-burst distribution and credit stalls
    # (write-side backpressure events), plus the closed per-reason
    # fallback table
    st = t.get("streams", {})
    streaming = {}
    if st:
        streaming = {
            "open": st.get("open", 0),
            "chunks_in": st.get("chunks_in", 0),
            "chunks_out": st.get("chunks_out", 0),
            "chunk_bytes_out": st.get("chunk_bytes_out", 0),
            "feedbacks_in": st.get("feedbacks_in", 0),
            "credit_stalls": st.get("credit_stalls", 0),
            "write_batches": st.get("write_batches", 0),
            "chunks_per_burst": _hist_view(
                st["chunk_burst"], st["chunk_burst_count"],
                st["chunk_burst_sum"]),
            "fallbacks": {k: v for k, v in st.get("fallbacks",
                                                  {}).items() if v},
        }
    out = {
        "lanes": lanes,
        "fallbacks": dict(top_fallbacks),
        "streaming": streaming,
        "client_lane": client_lane,
        "scatter_fallbacks": scatter_fallback_counters(),
        # deadline plane: per-(lane, method) doomed-work sheds — a
        # non-zero count means callers' budgets are dying in queue
        # (the bvar family deadline_shed_total carries the same data
        # to /vars and /metrics)
        "deadline_sheds": {f"{lane}|{method}": v for (lane, method), v
                           in sorted(shed_counters().items())},
        "burst": _hist_view(t["burst"], t["burst_count"],
                            t["burst_sum"]),
        "writev_iov": _hist_view(t["writev_iov"], t["writev_iov_count"],
                                 t["writev_iov_sum"]),
        "wq_hwm": t["wq_hwm"],
        "inbuf_hwm": t["inbuf_hwm"],
        # flat-scaling smoking gun: max−min of the windowed per-loop
        # busy ratios (0 on a one-loop engine) — mirrors the
        # native_engine_loop_busy_imbalance bvar
        "loop_busy_imbalance": round(
            bridge.telemetry.loop_busy_imbalance(), 4),
        "loops": loops,
        "methods": _per_target(t["methods"]),
        "routes": _per_target(t["routes"]),
    }
    return 200, "application/json", json.dumps(out, indent=1)


def _lm(server, msg, rest):
    """/lm — the serving-plane telemetry page (ISSUE 18): live decode
    sessions, recently finished session timelines, per-tier TTFT/ITL
    percentiles and SLO attainment, the batcher step-phase histograms,
    KV pool / prefix cache / host tier occupancy, and the WINDOWED
    spec-accept and prefix-hit ratios (current behavior — the lifetime
    cumulative keys stay on the bench/perf_guard plane).  One
    LmTelemetryCache window renders the whole page, same discipline as
    /native's one engine snapshot."""
    from ...models import lm_telemetry as lmt

    lm = None
    for (svc, mth), entry in sorted(server.methods.items()):
        if mth == "Decode" and hasattr(entry.service, "batcher"):
            lm = entry.service
            break
    cache = lmt.telemetry_cache()
    prev, cur, dt = cache.window()
    phases = {}
    for p, buckets in cur["phase_hists"].items():
        c = cur["phases"][p]
        tot = cur["phase_ns"][p]
        phases[p] = {
            "count": c,
            "avg_us": round(tot / c / 1e3, 1) if c else 0,
            "buckets_ns": {lmt.bucket_label(i): n
                           for i, n in enumerate(buckets) if n},
        }
    # scheduler event RATES over the cache window (the counters
    # themselves are on /vars as lm_slo_sched_total)
    sched_rate = {}
    if prev is not None:
        for k, v in cur["sched"].items():
            sched_rate[k] = round((v - prev["sched"].get(k, 0)) / dt, 2)
    # KV occupancy from the batcher that already exists — never
    # CREATE one from an observability page
    bat = getattr(lm, "_batcher", None) if lm is not None else None
    kv = bat.kv_stats() if bat is not None else {}
    out = {
        "live_sessions": cur["live"],
        "recent_sessions": cur["ring"][-32:],
        "ttft_ms": {f"{t}|{q}": v
                    for (t, q), v in sorted(cur["ttft_ms"].items())},
        "itl_ms": {f"{t}|{q}": v
                   for (t, q), v in sorted(cur["itl_ms"].items())},
        "slo_attained_total": {f"{t}|{v}": n for (t, v), n
                               in sorted(cur["slo"].items())},
        "phases": phases,
        "windowed": {
            "window_s": round(dt, 3),
            "spec_accept_rate":
                round(lmt.windowed_spec_accept_rate(cache), 4),
            "prefix_cache_hit_ratio":
                round(lmt.windowed_prefix_hit_ratio(cache), 4),
            "sched_rate_per_s": sched_rate,
        },
        "lifetime": {
            "spec_accept_rate":
                round(lmt.lifetime_spec_accept_rate(), 4),
            "prefix_cache_hit_ratio":
                round(lmt.lifetime_prefix_hit_ratio(), 4),
        },
        "sched": cur["sched"],
        "spec": cur["spec"],
        "prefix_events": cur["prefix_events"],
        "kv": kv,
        "timeline_ring": {"len": lmt.ring_len(),
                          "max": lmt.ring_maxlen()},
        "enabled": lmt.telemetry_enabled(),
    }
    return 200, "application/json", json.dumps(out, indent=1)


def _overload(server, msg, rest):
    """/overload — the admission plane's live state: per-(tenant,
    verdict) admission counters (closed verdict enum, no "unknown"
    bucket), per-tenant in-flight concurrency, the fair-admission
    configuration, per-method CoDel queue state, and every method's
    LIVE concurrency limit (adaptive limiters report their current
    value, not the static field)."""
    from ...butil.flags import get_flag
    from ..admission import admission_counters, tenant_inflight_snapshot

    ctl = server.admission
    methods = {}
    for (svc, mth), entry in sorted(server.methods.items()):
        st = entry.status
        methods[f"{svc}.{mth}"] = {
            "limiter": st.limiter_kind(),
            "max_concurrency": st.live_max_concurrency(),
            "inflight": st.inflight,
        }
    lim = server.server_limiter()
    mc = server.options.max_concurrency
    out = {
        "admission_total": {f"{t}|{v}": n for (t, v), n
                            in sorted(admission_counters().items())},
        "tenant_inflight": tenant_inflight_snapshot(),
        "fair_admission": {
            "enabled": bool(get_flag("enable_fair_admission", True)),
            "capacity": getattr(server.options, "tenant_fair_capacity",
                                0),
            "weights": dict(getattr(server.options, "tenant_weights",
                                    None) or {}),
        },
        "codel": {
            "enabled": bool(get_flag("enable_codel_shed", False)),
            "target_ms": get_flag("overload_codel_target_ms", 5.0),
            "interval_ms": get_flag("overload_codel_interval_ms", 100.0),
            "methods": ctl.codel_state(),
        },
        "server": {
            "max_concurrency": mc if isinstance(mc, int) else str(mc),
            "limiter": getattr(lim, "kind", None) if lim is not None
            else None,
            "live_limit": lim.max_concurrency() if lim is not None
            else (mc if isinstance(mc, int) else 0),
            "inflight": server.inflight,
        },
        "methods": methods,
    }
    return 200, "application/json", json.dumps(out, indent=1)


def _hotspots(server, msg, rest):
    """/hotspots/{cpu,contention,growth,heap,device,engine} — profilers.
    ≈ hotspots_service.cpp:35-40 (CPU/heap/growth/contention); device
    traces are the TPU-native addition (jax.profiler capture); engine
    samples the C++ loops' busy ratio, which the Python-thread
    profilers cannot see."""
    from ... import profiling
    from ...fiber.runtime import blocking

    q = msg.query()
    try:
        seconds = min(120.0, max(0.1, float(q.get("seconds", "5"))))
    except ValueError:
        return 400, "text/plain", "bad seconds\n"
    kind = rest[0] if rest else "cpu"
    with blocking():
        return _hotspots_run(server, q, kind, seconds)


def _hotspots_run(server, q, kind, seconds):
    """Profiler window bodies sleep for ``seconds`` — run under the
    fiber runtime's blocking() mark so the pool compensates."""
    from ... import profiling
    if kind == "cpu":
        try:
            hz = min(999, max(1, int(q.get("hz", "99"))))
        except ValueError:
            return 400, "text/plain", "bad hz\n"
        prof = profiling.sample_cpu(seconds=seconds, hz=hz)
        view = q.get("view", "flame")
        if view == "folded":
            return 200, "text/plain", profiling.render_folded(prof.folded)
        if view == "flat":
            return 200, "text/plain", profiling.render_flat(prof.folded)
        return 200, "text/html", profiling.render_flame_html(
            prof.folded,
            title=f"cpu profile — {seconds:.0f}s @ {hz}Hz "
                  f"({prof.samples} samples)")
    if kind == "contention":
        return 200, "text/plain", profiling.collect_contention(seconds)
    if kind == "growth":
        return 200, "text/plain", profiling.collect_growth(seconds)
    if kind == "heap":
        return 200, "text/plain", profiling.collect_heap()
    if kind == "engine":
        # C++ loop busy ratio over a sampled window: the engine loops
        # never appear in the Python-thread samplers above, yet they
        # ARE the data plane — time in callbacks vs epoll_wait is
        # their whole hotspot story (satellite of the telemetry PR)
        bridge = getattr(server, "_native_bridge", None)
        if bridge is None:
            return (200, "text/plain",
                    "no native engine loops on this server\n")
        a = bridge.engine.telemetry()["loops"]
        time.sleep(seconds)
        b = bridge.engine.telemetry()["loops"]
        lines = [f"native engine loops — {seconds:.1f}s window",
                 f"{'loop':>4} {'busy_ratio':>10} {'busy_ms':>9} "
                 f"{'idle_ms':>9} {'polls':>7}"]
        stuck = False
        for i, (la, lb) in enumerate(zip(a, b)):
            busy = lb["busy_ns"] - la["busy_ns"]
            idle = lb["idle_ns"] - la["idle_ns"]
            polls = lb["polls"] - la["polls"]
            denom = busy + idle
            # a loop that never re-entered epoll_wait during the window
            # spent ALL of it inside one callback (on an inline server
            # that includes the callback rendering this very page)
            ratio = busy / denom if denom else 1.0
            if denom == 0:
                stuck = True
            lines.append(
                f"{i:>4} {ratio:>10.4f} "
                f"{busy / 1e6:>9.1f} {idle / 1e6:>9.1f} {polls:>7}")
        if stuck:
            lines.append("(0-poll loop: the whole window ran inside a "
                         "single callback — on usercode_inline servers "
                         "this request itself occupies its loop)")
        return 200, "text/plain", "\n".join(lines) + "\n"
    if kind == "device":
        try:
            data, name = profiling.collect_device_trace(seconds)
        except Exception as e:
            return 500, "text/plain", f"device trace failed: {e}\n"
        return (200, "application/gzip", data,
                [("content-disposition", f"attachment; filename={name}")])
    return (404, "text/plain",
            "hotspots profilers: /hotspots/cpu?seconds=5&hz=99"
            "[&view=flame|flat|folded], /hotspots/contention?seconds=5, "
            "/hotspots/growth?seconds=5, /hotspots/heap, "
            "/hotspots/device?seconds=3, /hotspots/engine?seconds=5 "
            "(C++ loop busy ratio)\n")


def _sockets(server, msg, rest):
    """/sockets — live socket table (≈ builtin/sockets_service.cpp)."""
    from ...transport.socket import socket_pool

    lines = [f"{'id':>20} {'remote':<22} {'state':<8} "
             f"{'direct':<7} {'tag':<10} pending_writes", "-" * 80]
    for sid, s in socket_pool().live_items():
        try:
            state = "failed" if s.failed else "ok"
            remote = str(s.remote_side or "-")
            tag = str(getattr(s, "tag", None) or "-")
            direct = "yes" if getattr(s, "direct_read", False) else "no"
            pending = len(getattr(s, "_write_queue", ()) or ())
            lines.append(f"{sid:>20} {remote:<22} {state:<8} "
                         f"{direct:<7} {tag:<10} {pending}")
        except Exception:
            continue
    lines.append(f"\n{len(socket_pool())} live sockets")
    return 200, "text/plain", "\n".join(lines) + "\n"


def _threads(server, msg, rest):
    """/threads — all thread stacks (≈ builtin pstack via
    threads_service.cpp; here sys._current_frames + traceback)."""
    import threading as _threading
    import traceback as _tb

    names = {t.ident: t.name for t in _threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(line.rstrip() for line in _tb.format_stack(frame))
        out.append("")
    return 200, "text/plain", "\n".join(out) + "\n"


def _protobufs(server, msg, rest):
    """/protobufs — service/method schema listing (the reference lists
    registered pb descriptors; here the method registry + request types)."""
    out = {}
    for (svc, mth), entry in sorted(server.methods.items()):
        rt = entry.request_type
        out[f"{svc}.{mth}"] = {
            "request_type": getattr(rt, "__name__", str(rt))
            if rt is not None else "bytes",
            "grpc_streaming": bool(getattr(entry, "grpc_streaming", False)),
            # live limiter value, not the static field: with an
            # adaptive limiter installed the static max_concurrency is
            # 0 and used to (wrongly) report "unlimited" here
            "max_concurrency": entry.status.live_max_concurrency(),
            "concurrency_limiter": entry.status.limiter_kind(),
        }
    return 200, "application/json", json.dumps(out, indent=1)


def _vlog(server, msg, rest):
    """/vlog — inspect/set the framework log level
    (?setlevel=DEBUG|INFO|WARNING|ERROR)."""
    import logging as _logging

    from ...butil.logging_util import LOG as _LOG
    q = msg.query()
    if "setlevel" in q:
        name = q["setlevel"].upper()
        lvl = getattr(_logging, name, None)
        if not isinstance(lvl, int):
            return 400, "text/plain", f"unknown level {name!r}\n"
        _LOG.setLevel(lvl)
        return 200, "text/plain", f"log level set to {name}\n"
    return 200, "text/plain", \
        f"level={_logging.getLevelName(_LOG.level)}  " \
        f"(set with /vlog?setlevel=DEBUG)\n"


def _dir(server, msg, rest):
    """/dir — browse the server's working directory (read-only;
    ≈ builtin/dir_service.cpp)."""
    base = os.path.realpath(os.getcwd())
    target = os.path.realpath(os.path.join(base, *rest))
    if not target.startswith(base):
        return 403, "text/plain", "outside the working directory\n"
    if os.path.isdir(target):
        entries = sorted(os.listdir(target))
        rel = os.path.relpath(target, base)
        lines = [f"{rel if rel != '.' else '.'}/:"]
        for e in entries:
            full = os.path.join(target, e)
            mark = "/" if os.path.isdir(full) else \
                f"  ({os.path.getsize(full)} bytes)"
            lines.append(f"  {e}{mark}")
        return 200, "text/plain", "\n".join(lines) + "\n"
    if os.path.isfile(target):
        if os.path.getsize(target) > (8 << 20):
            return 403, "text/plain", "file too large\n"
        with open(target, "rb") as f:
            return 200, "application/octet-stream", f.read()
    return 404, "text/plain", "no such path\n"


def _trackme(server, msg, rest):
    """/trackme?ver=X — fleet version check-in (≈ trackme.cpp)."""
    from ...trackme import handle_trackme_query
    ver = msg.query().get("ver", "")
    return (200, "application/json",
            json.dumps(handle_trackme_query(ver)))


def _fleet(server, msg, rest):
    """/fleet — the fleet observability portal (ISSUE 19).

    Query modes:
      (none) / ?format=json   on a registry host: member table (state =
                              ok/draining/stale/seeded, report age,
                              slots/kv/slo/busy from the newest load
                              report), fleet SLO rollups + top-k
                              outliers, and the merged flight-recorder
                              timeline; on a plain member: this node's
                              own report + local event ring
      ?self=1                 this node's own load report (the
                              pull-on-demand path — same build the
                              KV.Probe tail and the cadence push share)
      ?trace_id=HEX           trace-index lookup: which member(s)
                              report the ROOT span of this trace
                              (rpcz_stitch seeds its BFS there)
    """
    from ... import fleet as fleet_mod
    q = msg.query()
    if q.get("self") == "1":
        report = fleet_mod.report_cache().get(server)
        return (200, "application/json",
                json.dumps(report, default=str, indent=1))
    reg = fleet_mod.registry_of(server)
    if "trace_id" in q:
        if reg is None:
            return 404, "text/plain", "no fleet registry on this server\n"
        tid = q["trace_id"].lower()
        return (200, "application/json",
                json.dumps({"trace_id": tid,
                            "owners": reg.trace_owners(tid)}))
    if reg is None:
        body = {"registry": False,
                "self": fleet_mod.report_cache().get(server),
                "events": fleet_mod.recent_events(64)}
        return (200, "application/json",
                json.dumps(body, default=str, indent=1))
    body = {
        "registry": True,
        "ttl_s": reg.ttl_s,
        "members": reg.members(),
        "rollups": reg.rollups(),
        "timeline": reg.timeline(128),
        "trace_index": reg.trace_index(),
    }
    return (200, "application/json",
            json.dumps(body, default=str, indent=1))


register_builtin("trackme", _trackme)
register_builtin("sockets", _sockets)
register_builtin("threads", _threads)
register_builtin("protobufs", _protobufs)
register_builtin("vlog", _vlog)
register_builtin("dir", _dir)
register_builtin("hotspots", _hotspots)
register_builtin("", _index)
register_builtin("index", _index)
register_builtin("health", _health)
register_builtin("version", _version)
register_builtin("status", _status)
register_builtin("vars", _vars)
register_builtin("list_vars", _list_vars)
register_builtin("brpc_metrics", _metrics)
register_builtin("metrics", _metrics)
register_builtin("flags", _flags)
register_builtin("connections", _connections)
register_builtin("fibers", _fibers)
register_builtin("rpcz", _rpcz)
register_builtin("native", _native)
register_builtin("overload", _overload)
register_builtin("lm", _lm)
register_builtin("fleet", _fleet)
