"""HTTP request routing: RPC bridge + builtin portal.

≈ the reference's http protocol dispatch (`/ServiceName/MethodName` →
service, everything else → builtin services on the same port,
/root/reference/src/brpc/policy/http_rpc_protocol.cpp + server.cpp:464).
JSON bridge: a dict/list return value is serialized as JSON; a JSON body
arrives as bytes for the method to parse (json2pb's role without
protobuf codegen in the way).
"""

from __future__ import annotations

import json
from typing import Any, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..butil.time_utils import monotonic_us
from ..protocol.http import HttpMessage, build_response
from ..protocol.meta import RpcMeta
from ..transport.socket import Socket
from .controller import ServerController


PUBLIC_BUILTIN_PAGES = ("health", "version")


def portal_restricted(server, sock, first_segment: str) -> bool:
    """True when builtin pages must be refused on this connection: an
    internal port is configured, this connection is not on it, and the
    page is not in the public allowlist (shared by HTTP/1 and h2)."""
    return (server.options.internal_port >= 0
            and getattr(sock, "tag", None) != "internal"
            and first_segment not in PUBLIC_BUILTIN_PAGES)


def handle_http_request(msg: HttpMessage, sock, server) -> None:
    path = msg.path.rstrip("/") or "/"
    parts = [p for p in path.split("/") if p]
    # RPC bridge: /Service/Method (also /Service.Method for symmetry)
    entry = None
    if len(parts) == 2:
        entry = server.find_method(parts[0], parts[1])
        svc, mth = parts[0], parts[1]
    elif len(parts) == 1 and "." in parts[0]:
        svc, _, mth = parts[0].partition(".")
        entry = server.find_method(svc, mth)
    if entry is not None:
        _bridge_rpc(msg, sock, server, svc, mth, entry)
        return
    # With an internal port configured, operator pages are reachable only
    # through it (≈ reference's internal-port-only builtin services);
    # liveness probes stay public.
    if portal_restricted(server, sock, parts[0] if parts else ""):
        sock.write(build_response(
            403, b"builtin services are restricted to the internal port\n",
            keep_alive=msg.keep_alive))
        return
    from .builtin import route_builtin
    try:
        status, ctype, body, extra = route_builtin(server, msg)
    except Exception as e:
        LOG.exception("builtin page %s raised", msg.path)
        status, ctype, body, extra = 500, "text/plain", \
            f"internal error: {e}\n".encode(), []
    sock.write(build_response(status, body, ctype, headers=extra,
                              keep_alive=msg.keep_alive))


def _bridge_rpc(msg: HttpMessage, sock, server, svc: str,
                mth: str, entry) -> None:
    if not server.on_request_in():
        sock.write(build_response(503, b"server max_concurrency",
                                  keep_alive=msg.keep_alive))
        return
    if not entry.status.on_requested():
        server.on_request_out()
        sock.write(build_response(503, b"method max_concurrency",
                                  keep_alive=msg.keep_alive))
        return

    meta = RpcMeta()
    meta.service_name = svc
    meta.method_name = mth

    def send(cntl: ServerController, response: Any) -> None:
        latency_us = monotonic_us() - cntl.begin_time_us
        entry.status.on_responded(cntl.error_code, latency_us)
        server.on_request_out()
        s = Socket.address(cntl.socket_id)
        if s is None:
            return
        if cntl.failed:
            code = 400 if cntl.error_code in (int(Errno.EREQUEST),) else 500
            s.write(build_response(
                code, cntl.error_text.encode(),
                headers=[("x-rpc-error-code", str(cntl.error_code))],
                keep_alive=msg.keep_alive))
            return
        body, ctype = _encode_http_body(response)
        extra = None
        att = cntl.response_attachment.to_bytes() \
            if len(cntl.response_attachment) else b""
        if att:
            # attachment rides after the body; the size header lets the
            # peer split (HTTP has no native side channel)
            body += att
            extra = [("x-rpc-attachment-size", str(len(att)))]
        s.write(build_response(200, body, ctype, headers=extra,
                               keep_alive=msg.keep_alive))

    cntl = ServerController(meta, sock.remote_side, sock.id, send)
    cntl.server = server
    if msg.method in ("GET", "HEAD") and msg.query_string:
        request: Any = json.dumps(msg.query()).encode()
    else:
        request = msg.body
        att_size = msg.headers.get("x-rpc-attachment-size")
        if att_size and att_size.isdigit():
            n = int(att_size)
            if 0 < n <= len(request):
                cntl.request_attachment = IOBuf(request[len(request) - n:])
                request = request[:len(request) - n]
    try:
        from ..protocol.tpu_std import parse_payload
        request = parse_payload(request, entry.request_type)
    except Exception as e:
        cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
        cntl.finish(None)
        return
    try:
        response = entry.fn(cntl, request)
    except Exception as e:
        LOG.exception("http method %s raised", entry.status.full_name)
        cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.finish(None)
        return
    if cntl.is_async:
        return
    cntl.finish(response)


def _encode_http_body(response: Any) -> Tuple[bytes, str]:
    if response is None:
        return b"", "text/plain"
    if isinstance(response, (dict, list)):
        return json.dumps(response).encode(), "application/json"
    if isinstance(response, str):
        return response.encode(), "text/plain"
    if isinstance(response, IOBuf):
        return response.to_bytes(), "application/octet-stream"
    if isinstance(response, (bytes, bytearray, memoryview)):
        return bytes(response), "application/octet-stream"
    if hasattr(response, "SerializeToString"):
        return response.SerializeToString(), "application/x-protobuf"
    return str(response).encode(), "text/plain"
