"""HTTP request routing: RPC bridge + builtin portal.

≈ the reference's http protocol dispatch (`/ServiceName/MethodName` →
service, everything else → builtin services on the same port,
/root/reference/src/brpc/policy/http_rpc_protocol.cpp + server.cpp:464).
JSON bridge: a dict/list return value is serialized as JSON; a JSON body
arrives as bytes for the method to parse (json2pb's role without
protobuf codegen in the way).
"""

from __future__ import annotations

import json
from typing import Any, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..deadline import inherit_deadline
from ..protocol.http import HttpMessage, build_response
from ..transport.socket import Socket
from .controller import ServerController


PUBLIC_BUILTIN_PAGES = ("health", "version")


def drain_response_args(server, headers=None, keep_alive=True):
    """Operability plane, HTTP spelling: while the server drains,
    every HTTP/1.1 response — success, rejection, builtin page —
    carries ``x-lame-duck: 1`` and ``Connection: close`` (the
    keep-alive teardown makes the client re-connect, and its resolver
    will land elsewhere).  Returns the adjusted ``(headers,
    keep_alive)`` pair; a no-op outside drain, so the lanes stay
    byte-identical in steady state."""
    if server is not None and server.lame_duck_signal_on:
        h = list(headers or [])
        if not any(k.lower() == "x-lame-duck" for k, _v in h):
            h.append(("x-lame-duck", "1"))   # /health already adds its
            #                                  own — never duplicate
        return h, False
    return headers, keep_alive


def http_status_for_error(error_code: int) -> int:
    """RPC error -> HTTP status for the bridge (shared with the slim
    HTTP lane, server/http_slim.py — the two must map identically for
    the lanes to stay byte-identical)."""
    return 400 if error_code == int(Errno.EREQUEST) else 500


def portal_restricted(server, sock, first_segment: str) -> bool:
    """True when builtin pages must be refused on this connection: an
    internal port is configured, this connection is not on it, and the
    page is not in the public allowlist (shared by HTTP/1 and h2)."""
    return (server.options.internal_port >= 0
            and getattr(sock, "tag", None) != "internal"
            and first_segment not in PUBLIC_BUILTIN_PAGES)


class ProgressiveAttachment:
    """Chunked-transfer body writer living past the RPC
    (≈ /root/reference/src/brpc/progressive_attachment.h): the handler
    calls cntl.create_progressive_attachment(), returns, then any thread
    writes chunks and close()s.  The connection carries the chunk stream
    until then."""

    def __init__(self, socket_id: int):
        import threading as _threading
        self._socket_id = socket_id
        self._closed = False
        self._started = False           # headers on the wire yet?
        self._pending = []              # chunks written before that
        self._lock = _threading.Lock()

    def _start(self) -> None:
        """Called by the dispatcher once the response headers are out:
        flush chunks the handler raced ahead with.  The flush stays
        under the lock so a concurrent write() cannot jump ahead of the
        buffered frames (Socket.write is ordered; this lock orders who
        reaches it first)."""
        with self._lock:
            self._started = True
            pending, self._pending = self._pending, []
            s = Socket.address(self._socket_id)
            if s is not None and not s.failed:
                for frame in pending:
                    s.write(IOBuf(frame))

    def _abort(self) -> None:
        """RPC failed before the chunked response started: kill the
        attachment so background writers see ECLOSE instead of buffering
        forever."""
        with self._lock:
            self._closed = True
            self._pending.clear()

    def write(self, data) -> int:
        """One HTTP/1.1 chunk; returns 0 or an errno."""
        b = bytes(data)
        if not b:
            return 0
        frame = b"%x\r\n" % len(b) + b + b"\r\n"
        with self._lock:
            if self._closed:
                return int(Errno.ECLOSE)
            if not self._started:
                self._pending.append(frame)
                return 0
            s = Socket.address(self._socket_id)
            if s is None or s.failed:
                return int(Errno.EFAILEDSOCKET)
            return s.write(IOBuf(frame))

    def close(self) -> None:
        """Terminal zero chunk; idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if not self._started:
                self._pending.append(b"0\r\n\r\n")
                return
            s = Socket.address(self._socket_id)
            if s is not None and not s.failed:
                s.write(IOBuf(b"0\r\n\r\n"))

    @property
    def closed(self) -> bool:
        return self._closed


def handle_http_request(msg: HttpMessage, sock, server) -> None:
    path = msg.path.rstrip("/") or "/"
    parts = [p for p in path.split("/") if p]
    # RPC bridge: /Service/Method (also /Service.Method for symmetry)
    entry = None
    unresolved = ""
    if len(parts) == 2:
        entry = server.find_method(parts[0], parts[1])
        svc, mth = parts[0], parts[1]
    elif len(parts) == 1 and "." in parts[0]:
        svc, _, mth = parts[0].partition(".")
        entry = server.find_method(svc, mth)
    if entry is None and server._restful:
        hit = server.find_restful(parts)
        if hit is not None:
            entry, unresolved = hit
            svc = entry.status.full_name.rsplit(".", 1)[0]
            mth = entry.method_name
    if entry is not None:
        _bridge_rpc(msg, sock, server, svc, mth, entry,
                    unresolved=unresolved)
        return
    # With an internal port configured, operator pages are reachable only
    # through it (≈ reference's internal-port-only builtin services);
    # liveness probes stay public.
    if portal_restricted(server, sock, parts[0] if parts else ""):
        sock.write(build_response(
            403, b"builtin services are restricted to the internal port\n",
            keep_alive=msg.keep_alive))
        return
    from .builtin import route_builtin
    try:
        status, ctype, body, extra = route_builtin(server, msg)
    except Exception as e:
        LOG.exception("builtin page %s raised", msg.path)
        status, ctype, body, extra = 500, "text/plain", \
            f"internal error: {e}\n".encode(), []
    extra, ka = drain_response_args(server, extra, msg.keep_alive)
    sock.write(build_response(status, body, ctype, headers=extra,
                              keep_alive=ka))


def _bridge_rpc(msg: HttpMessage, sock, server, svc: str,
                mth: str, entry, unresolved: str = "") -> None:
    # cross-cutting stages (admission → trace extract → deadline
    # arm/shed) ride the COMPILED interceptor chain — the third
    # binding of ROADMAP item 1 (after the kind-5 streaming and kind-3
    # slim lanes).  The lane body only builds its HTTP-flavored send
    # closure, calls the chain's enter before user code, and settles
    # every completion through the chain's settle half.
    chain = getattr(entry, "_http_chain", None)
    if chain is None:
        from .interceptors import compile_http_chain
        chain = compile_http_chain(server, entry)
        try:
            entry._http_chain = chain       # compile once per entry
        except AttributeError:
            pass
    _enter, _settle = chain

    def send(cntl: ServerController, response: Any) -> None:
        s = Socket.address(cntl.socket_id)
        if s is None:
            _settle(cntl, 0)
            return
        if cntl.failed:
            if cntl._progressive is not None:
                cntl._progressive._abort()
            code = http_status_for_error(cntl.error_code)
            body = cntl.error_text.encode()
            _settle(cntl, len(body))
            hdrs, ka = drain_response_args(
                server, [("x-rpc-error-code", str(cntl.error_code))],
                msg.keep_alive)
            s.write(build_response(code, body, headers=hdrs,
                                   keep_alive=ka))
            return
        if cntl._progressive is not None:
            # chunked transfer: headers now, body chunks whenever the
            # ProgressiveAttachment writes them
            body, ctype = _encode_http_body(response)
            head = (b"HTTP/1.1 200 OK\r\n"
                    b"content-type: " + ctype.encode() + b"\r\n"
                    b"transfer-encoding: chunked\r\n"
                    b"connection: keep-alive\r\n\r\n")
            first = b"%x\r\n" % len(body) + body + b"\r\n" if body else b""
            s.write(IOBuf(head + first))
            cntl._progressive._start()
            _settle(cntl, len(body))
            return
        body, ctype = _encode_http_body(response)
        extra = None
        att = cntl.response_attachment.to_bytes() \
            if len(cntl.response_attachment) else b""
        if att:
            # attachment rides after the body; the size header lets the
            # peer split (HTTP has no native side channel)
            body += att
            extra = [("x-rpc-attachment-size", str(len(att)))]
        _settle(cntl, len(body))
        extra, ka = drain_response_args(server, extra, msg.keep_alive)
        s.write(build_response(200, body, ctype, headers=extra,
                               keep_alive=ka))

    cntl = _enter(msg, sock, svc, mth, unresolved, send)
    if cntl is None:
        return           # rejected or shed: the client is answered
    if msg.method in ("GET", "HEAD") and msg.query_string:
        request: Any = json.dumps(msg.query()).encode()
    else:
        request = msg.body
        att_size = msg.headers.get("x-rpc-attachment-size")
        if att_size and att_size.isdigit():
            n = int(att_size)
            if 0 < n <= len(request):
                cntl.request_attachment = IOBuf(request[len(request) - n:])
                request = request[:len(request) - n]
    try:
        from ..protocol.json2pb import maybe_parse_request
        converted = maybe_parse_request(
            request if isinstance(request, bytes) else bytes(request),
            entry.request_type, msg.headers.get("content-type", ""))
        if converted is not None:
            request = converted          # json2pb: JSON → pb message
        else:
            from ..protocol.tpu_std import parse_payload
            request = parse_payload(request, entry.request_type)
    except Exception as e:
        cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
        cntl.finish(None)
        return
    try:
        with inherit_deadline(cntl):
            response = entry.fn(cntl, request)
    except Exception as e:
        LOG.exception("http method %s raised", entry.status.full_name)
        cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.finish(None)
        return
    if cntl.is_async:
        return
    cntl.finish(response)


def _encode_http_body(response: Any) -> Tuple[bytes, str]:
    if response is None:
        return b"", "text/plain"
    from ..protocol.json2pb import maybe_encode_response
    as_json = maybe_encode_response(response)
    if as_json is not None:              # pb message → JSON (pb2json)
        return as_json, "application/json"
    if isinstance(response, (dict, list)):
        return json.dumps(response).encode(), "application/json"
    if isinstance(response, str):
        return response.encode(), "text/plain"
    if isinstance(response, IOBuf):
        return response.to_bytes(), "application/octet-stream"
    if isinstance(response, (bytes, bytearray, memoryview)):
        return bytes(response), "application/octet-stream"
    if hasattr(response, "SerializeToString"):
        return response.SerializeToString(), "application/x-protobuf"
    return str(response).encode(), "text/plain"
