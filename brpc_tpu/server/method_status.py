"""Per-method stats + concurrency accounting
(≈ /root/reference/src/brpc/details/method_status.h): every method gets a
LatencyRecorder (qps/latency/percentiles in windows), an error counter,
and an in-flight gauge the concurrency limiter reads."""

from __future__ import annotations

import threading
from typing import Optional

from ..bvar.latency_recorder import LatencyRecorder
from ..bvar.reducer import Adder


class MethodStatus:
    __slots__ = ("full_name", "latency", "errors", "_inflight",
                 "_inflight_lock", "max_concurrency", "limiter")

    def __init__(self, full_name: str, max_concurrency: int = 0,
                 limiter=None):
        safe = full_name.replace(".", "_").lower()
        self.full_name = full_name
        self.latency = LatencyRecorder(f"rpc_server_{safe}")
        self.errors = Adder(f"rpc_server_{safe}_error")
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.max_concurrency = max_concurrency
        self.limiter = limiter

    def on_requested(self) -> bool:
        """≈ ConcurrencyLimiter::OnRequested via MethodStatus. Returns
        False to reject (ELIMIT)."""
        with self._inflight_lock:
            limit = (self.limiter.max_concurrency()
                     if self.limiter is not None else self.max_concurrency)
            if limit > 0 and self._inflight >= limit:
                return False
            self._inflight += 1
            return True

    def undo_requested(self) -> None:
        """Back out one on_requested that a LATER admission layer
        (CoDel / tenant quota) vetoed: the request never ran, so no
        latency/error sample reaches the limiter."""
        with self._inflight_lock:
            if self._inflight > 0:
                self._inflight -= 1

    def live_max_concurrency(self) -> int:
        """The limit admission actually enforces right now: the
        adaptive limiter's live value when one is installed, else the
        static cap (0 = unlimited).  The /status page reports this —
        a static 0 next to an installed AutoLimiter used to read as
        'unlimited'."""
        if self.limiter is not None:
            return self.limiter.max_concurrency()
        return self.max_concurrency

    def limiter_kind(self) -> str:
        """'auto' / 'timeout' / 'constant' when a limiter is installed,
        'constant' for a bare max_concurrency cap, 'unlimited' else."""
        if self.limiter is not None:
            return getattr(self.limiter, "kind", "custom")
        return "constant" if self.max_concurrency > 0 else "unlimited"

    def on_responded(self, error_code: int, latency_us: float) -> None:
        with self._inflight_lock:
            if self._inflight > 0:
                self._inflight -= 1
        if error_code == 0:
            self.latency << latency_us
        else:
            self.errors << 1
        if self.limiter is not None:
            self.limiter.on_responded(error_code, latency_us)

    @property
    def inflight(self) -> int:
        return self._inflight
