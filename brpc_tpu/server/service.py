"""Service definition layer.

The reference dispatches protobuf-generated service stubs
(google::protobuf::Service); this framework is Python-first: a Service is
any object whose public methods take ``(controller, request)`` and return
the response (or None for async completion via
``controller.begin_async()`` + ``controller.finish(resp)``).

Request typing: by default requests arrive as raw ``bytes``; a method can
declare a richer type with the :func:`method` decorator — anything with
``ParseFromString`` (protobuf) or ``parse`` (framework light messages).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional


def method(request_type: Any = None, response_compress: int = 0):
    """Decorator declaring per-method options:

        class Search(Service):
            @method(request_type=SearchRequest)
            def Query(self, cntl, request): ...
    """
    def mark(fn: Callable) -> Callable:
        fn._rpc_request_type = request_type
        fn._rpc_response_compress = response_compress
        return fn
    return mark


class Service:
    """Optional base class; any duck-typed object works via
    :func:`extract_methods`."""

    @classmethod
    def service_name(cls) -> str:
        return cls.__name__


def extract_methods(service: Any) -> Dict[str, Callable]:
    """Public callables of the service object = its RPC methods."""
    out: Dict[str, Callable] = {}
    for name in dir(service):
        if name.startswith("_"):
            continue
        fn = getattr(service, name)
        if not callable(fn):
            continue
        if name in ("service_name",):
            continue
        # only functions defined by the service class (not inherited
        # object/Service plumbing)
        if inspect.ismethod(fn) or inspect.isfunction(fn):
            out[name] = fn
    return out


def service_name_of(service: Any) -> str:
    if hasattr(service, "service_name"):
        return service.service_name()
    return type(service).__name__
