"""Service definition layer.

The reference dispatches protobuf-generated service stubs
(google::protobuf::Service); this framework is Python-first: a Service is
any object whose public methods take ``(controller, request)`` and return
the response (or None for async completion via
``controller.begin_async()`` + ``controller.finish(resp)``).

Request typing: by default requests arrive as raw ``bytes``; a method can
declare a richer type with the :func:`method` decorator — anything with
``ParseFromString`` (protobuf) or ``parse`` (framework light messages).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional


def method(request_type: Any = None, response_compress: int = 0):
    """Decorator declaring per-method options:

        class Search(Service):
            @method(request_type=SearchRequest)
            def Query(self, cntl, request): ...
    """
    def mark(fn: Callable) -> Callable:
        fn._rpc_request_type = request_type
        fn._rpc_response_compress = response_compress
        return fn
    return mark


def raw_method(fn: Callable = None, *, native: str = None) -> Callable:
    """Declare a RAW method — the latency lane's server half.

    Signature: ``(payload, attachment) -> response`` where payload and
    attachment are zero-copy buffers (memoryview into the transport's
    frame; attachment is None when the request carried none) and the
    return is ``bytes`` or ``(response_bytes, attachment_bytes)``.

    Raw methods dispatch without a ServerController, span, or payload
    re-materialisation: on the native transport the whole turnaround is
    frame-parse → handler → flat-TLV response (the ≈200-300ns-handler
    discipline of /root/reference/docs/cn/benchmark.md:57, within
    Python's reach).  Per-method stats and concurrency admission still
    apply.  Passive rpcz sampling skips the slim path (that is the
    lane's contract); explicitly traced requests (non-zero trace id),
    live rpc_dump capture, and requests carrying controller-tier
    features (compression, device descriptors, streams, auth,
    interceptors) fall back to the full dispatch, where the handler is
    invoked with the same (payload, attachment) shape.

    Deadline contract: the request's remaining-deadline TLV is accepted
    but NOT enforced on the raw path — raw handlers receive no
    controller to answer ``ERPCTIMEDOUT`` through or to propagate the
    budget further.  Note that "cannot have expired at arrival" is NOT
    true on this lane: burst-batched native dispatch demonstrably
    queues frames between parse and handler (rpcz ``backdate_span``
    pins non-zero native queueing), so a raw method under deadline
    pressure silently does doomed work.  Handlers needing deadline
    semantics belong on the full ``@method`` path, where EVERY dispatch
    route — classic tpu_std, the slim kind-3/kind-4 native shims, HTTP
    and gRPC/h2 — sheds queue-expired requests before user code runs
    (anchored at the engine's parse timestamp on the native lanes) and
    exposes ``cntl.deadline_remaining_ms()`` / ``cntl.deadline_expired``
    (see brpc_tpu.deadline; ≈ brpc ``-server_fail_fast``).

    ``native=``: name a C++ built-in semantic and the native engine
    answers the method entirely GIL-free — zero Python per request, the
    analogue of the reference's built-in C++ services.  The Python
    ``fn`` is the behavioral spec AND the live fallback (Python
    transport, live rpc_dump capture, concurrency limits, controller-
    tier request features); it must implement exactly the declared
    semantic:

      - ``"echo"``: respond with the request payload and attachment
        unchanged
      - ``"const"``: respond with the fixed bytes the handler returns
        when called with (b"", None) — captured once at server start

        class Echo(Service):
            @raw_method(native="echo")
            def Echo(self, payload, attachment):
                return payload, attachment
    """
    def mark(f: Callable) -> Callable:
        f._rpc_raw = True
        f._rpc_native = native
        return f
    return mark(fn) if fn is not None else mark


def grpc_streaming(fn: Callable) -> Callable:
    """Declare a gRPC STREAMING method (server/client/bidi — the wire
    doesn't distinguish; the handler shape does):

        class Chat(Service):
            @grpc_streaming
            def Talk(self, cntl, msgs):       # msgs: iterator of requests
                for m in msgs:                # client/bidi streaming
                    cntl.grpc_stream.write(m) # server pushes
                return None                   # or a final response message

    The handler runs as soon as request HEADERS arrive; request messages
    stream in through ``msgs`` (ends when the client half-closes); every
    ``cntl.grpc_stream.write(bytes)`` pushes one response message; a
    non-None return value is sent as a final message before trailers.
    ≈ the reference's full-duplex h2 streams
    (/root/reference/src/brpc/policy/http2_rpc_protocol.cpp + grpc.h).
    """
    fn._grpc_streaming = True
    return fn


class Service:
    """Optional base class; any duck-typed object works via
    :func:`extract_methods`."""

    @classmethod
    def service_name(cls) -> str:
        return cls.__name__


def extract_methods(service: Any) -> Dict[str, Callable]:
    """Public callables of the service object = its RPC methods."""
    out: Dict[str, Callable] = {}
    for name in dir(service):
        if name.startswith("_"):
            continue
        fn = getattr(service, name)
        if not callable(fn):
            continue
        if name in ("service_name",):
            continue
        # only functions defined by the service class (not inherited
        # object/Service plumbing)
        if inspect.ismethod(fn) or inspect.isfunction(fn):
            out[name] = fn
    return out


def service_name_of(service: Any) -> str:
    if hasattr(service, "service_name"):
        return service.service_name()
    return type(service).__name__
