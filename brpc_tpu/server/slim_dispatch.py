"""Slim native server-side dispatch — the Python half of the engine's
kind-3 lane.

The reference runs the ENTIRE request path to the user callback in C++
(/root/reference/src/brpc/policy/baidu_rpc_protocol.cpp:314-536); here
the C++ engine scans the meta TLV, batches every eligible unary request
of a read burst, and enters Python ONCE calling the shim built below as
``handler(payload: bytes, att: bytes | None, cid: int, conn_id: int,
dom, nonce, recv_ns: int, trace, timeout_ms, tenant)`` — ``recv_ns`` is the
engine's CLOCK_MONOTONIC frame-parse timestamp, used to backdate rpcz
spans so they cover native queueing; ``trace`` is None or the request's
``(trace_id, span_id, parent_id)`` meta TLVs, so explicitly traced
requests STAY on the slim lane instead of changing the very path being
observed; ``timeout_ms`` is TLV 13's propagated remaining budget
(None = no deadline on the wire; an explicit 0 means expired at
arrival) — anchored at ``recv_ns``, the shim SHEDS requests whose
budget expired while they sat in the native batch (deadline plane:
the handler never runs; the client gets ``ERPCTIMEDOUT``); ``tenant``
is TLV 22's identity bytes (None = untenanted), the fair-admission
key.  The shim is the whole per-call Python cost of the lane:

    admission   the SHARED overload-plane stage (server/admission.py):
                server cap, adaptive per-method concurrency, CoDel
                queue discipline against the engine parse stamp, and
                per-tenant fair admission — ELIMIT answers are sent
                through the classic error builder (byte-identical);
                the method limiters are fed parse-stamp latencies, so
                native batch queueing counts against the limit
    sampling    rpcz spans keep their per-second budget via
                start_server_span; traced requests (non-zero trace
                context) always record; span sizes are recorded INLINE
                on the slim completion — sampling a call no longer
                escalates it off the lane
    user code   entry.fn(cntl, request) with a REAL ServerController —
                handlers keep attachments, set_failed, begin_async,
                session_local_data, annotate, everything
    accounting  MethodStatus.on_responded with the measured latency

Return contract with the engine (flush_py_batch, kind 3):

    bytes / memoryview      success payload; frame built natively and
                            coalesced into the burst's single writev
    (payload, att_bytes)    success with response attachment
    None                    the shim completed (or will complete, for
                            async methods) the RPC through the classic
                            Python send path — byte-identical fallback

Everything the slim frame cannot express natively escalates through
``cntl.finish`` into rpc_dispatch._send_response, so escalated calls
are byte-identical with the classic path by construction: async
completion, compressed/streamed/device responses, non-bytes responses,
errors.  Request-side ineligibility (compression, streams, device
descriptors, over-threshold attachments, large frames) never reaches
the shim — the engine's meta scan routes those frames to the classic
path.  Trace context is NOT an ineligibility: the engine hands it
through ``trace`` and the span completes on the lane.
"""

from __future__ import annotations

import sys
from time import monotonic_ns as _mono_ns

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..deadline import arm as arm_deadline
from ..deadline import inherit_deadline, maybe_shed
from ..protocol.meta import RpcMeta
from ..protocol.tpu_std import parse_payload
from ..rpcz import backdate_span, passive_server_span
from .admission import count_admitted_burst, trivial_shape
from .controller import ServerController
from .interceptors import compile_chain
from .rpc_dispatch import _send_error, _send_response

# per-entry pooled-controller cap: enough to cover a whole engine read
# burst of in-flight fast completions without unbounded retention
_SC_POOL_MAX = 64

# Per-burst aggregated accounting (the ISSUE-8 "per-burst aggregates
# where semantics allow"): each engine loop thread accumulates its
# burst's admitted-verdict count here and the engine's burst_end hook
# (NativeBridge registers flush_burst_accounting) folds it into the
# module-global admission counters under ONE lock per burst.  Thread-
# local: engine loops never race each other's accumulator.
import threading as _threading

_burst_tls = _threading.local()


def _burst_cell() -> list:
    cell = getattr(_burst_tls, "admitted", None)
    if cell is None:
        cell = _burst_tls.admitted = [0]
    return cell


def flush_burst_accounting() -> None:
    """Engine burst_end hook: flush this loop thread's aggregated
    fast-path accounting (called once per batched GIL entry)."""
    cell = getattr(_burst_tls, "admitted", None)
    if cell is not None and cell[0]:
        count_admitted_burst(cell[0])
        cell[0] = 0

_EINTERNAL = int(Errno.EINTERNAL)
_EREQUEST = int(Errno.EREQUEST)
_ELIMIT = int(Errno.ELIMIT)
_ELOGOFF = int(Errno.ELOGOFF)


def make_slim_handler(bridge, server, entry, svc: str, mth: str):
    """Build the kind-3 shim for one (service, method) entry.  All
    per-entry state is bound into default args — the steady-state call
    touches no module globals.

    Since the interceptor-chain port (ROADMAP item 1, second binding
    after the kind-5 stream lane): the non-trivial request path runs
    through the compiled chain (server/interceptors.py) — ``enter``
    before user code, ``settle`` after — so admission ordering, trace
    extraction, deadline shed and the MethodStatus epilogue live in ONE
    place and the lane linter checks the binding, not a copy.  The
    precompiled fast template below it is the documented exception: it
    serves only trivial shapes (no trace/tenant TLVs, no admission
    layer configured), where the chain's stages are each provably
    no-ops and the per-call cost is the whole point."""
    status = entry.status
    fn = entry.fn
    req_type = entry.request_type
    full_name = status.full_name
    socks = bridge._socks          # conn_id -> NativeSocket (live dict)
    enter, settle = compile_chain(server, entry, "slim")

    # one shared completion closure (not one lambda per call): it only
    # reads its (cntl, response) arguments
    def _send(cntl, response, _server=server, _entry=entry):
        _send_response(_server, _entry, cntl, response)

    # fast-template state: a reset-on-reuse (ServerController + RpcMeta)
    # free list.  The meta's service/method names are per-entry
    # constants set once; reuse resets every field the fast path can
    # touch (cid, attachment size, deadline, ici domain) and
    # reset_slim() restores the controller wholesale.
    sc_pool: list = []

    # ARITY CONTRACT (machine-checked): the engine's kind-3 call site
    # passes exactly the public params below (privates are the
    # underscore-prefixed default binds) — tools/check gates both sides
    def slim(payload, att, cid, conn_id, dom, nonce, recv_ns,
             trace=None, tmo=None, tenant=None,
             _server=server, _entry=entry, _status=status, _fn=fn,
             _rt=req_type,
             _svc=svc, _mth=mth, _send=_send, _socks=socks,
             _ns=_mono_ns,
             _backdate=backdate_span, _shed=maybe_shed,
             _inherit=inherit_deadline, _arm=arm_deadline,
             _pool=sc_pool,
             _trivial=trivial_shape, _refs=sys.getrefcount,
             _cell=_burst_cell, _pspan=passive_server_span,
             _enter=enter, _settle=settle):
        sock = _socks.get(conn_id)
        if sock is None:
            return None          # connection died mid-burst: drop, like
                                 # the classic path drops dead-conn sends
        if not _server.running:
            _send_error(sock, cid, _ELOGOFF, "server is stopping")
            return None
        fast = trace is None and tenant is None \
            and _trivial(_server, _status)
        if not fast:
            # ---- the interceptor-chain binding (ROADMAP item 1): the
            # cross-cutting stages — admission → deadline shed → trace
            # extract, in pinned order — run INSIDE enter; a None
            # return means the client is already answered (rejection /
            # shed: ELIMIT/ELAMEDUCK ride the shared classic error
            # builder, byte-identical with every other lane) and every
            # taken count is settled.  The stages measure from the
            # ENGINE's CLOCK_MONOTONIC parse stamp, so native batch
            # queueing counts against limits, deadlines and spans
            cntl = _enter(sock, cid, len(payload), att, dom, nonce,
                          recv_ns, trace, tmo, tenant)
            if cntl is None:
                return None
            try:
                request = parse_payload(payload, _rt)
            except Exception as e:
                cntl.set_failed(Errno.EREQUEST,
                                f"request parse failed: {e}")
                cntl.finish(None)
                return None
            try:
                with _inherit(cntl):
                    response = _fn(cntl, request)
            except Exception as e:
                LOG.exception("method %s raised", _status.full_name)
                cntl.set_failed(Errno.EINTERNAL,
                                f"{type(e).__name__}: {e}")
                cntl.finish(None)
                return None
            if cntl.is_async:
                return None      # user owns completion via cntl.finish
            if (cntl.failed or cntl._accepted_stream_id
                    or cntl.response_compress_type
                    or cntl.response_device_attachment is not None
                    or not isinstance(response,
                                      (bytes, bytearray, memoryview))):
                # anything the native frame builder cannot express:
                # classic completion — byte-identical by construction
                cntl.finish(response)
                return None
            # ---- slim fast completion: chain epilogue + native frame
            if not cntl._mark_finished_if_first():
                return None
            ratt = cntl._resp_att
            na_resp = len(ratt) if ratt is not None else 0
            _settle(cntl, len(response) + na_resp)
            if na_resp:
                # zero-copy handoff: the engine pins the returned
                # buffer (Py_buffer) for the writev — a single-block
                # attachment materializes nothing here
                return response, ratt.as_contiguous()[0]
            return response
        # ---- precompiled fast template (the per-call cost collapse the
        # client lane's acceptance keys measure): for the hot request
        # shape — no trace/tenant TLVs — on a method with NO admission
        # layer configured, the per-call RpcMeta build, the four-layer
        # admit() walk and the ServerController construction are
        # replaced by pooled reset-on-reuse objects, and admission
        # accounting aggregates per BURST (admitted verdicts flush in
        # the engine's burst_end hook; in-flight gauges are net-zero
        # across a synchronously-completing item and are not touched —
        # they stay exact whenever any admission layer is configured).
        # This is the ONE documented exception to the chain binding
        # above: every chain stage is a provable no-op for this shape
        # (no admission layers, no trace context, passive sampling
        # only), so skipping the chain changes cost, not semantics.
        # Every escalation shape (async, errors, compressed/device/
        # stream responses, non-bytes returns) leaves through the
        # UNCHANGED classic completion, and the escalated controller is
        # simply not recycled.
        _cell()[0] += 1
        try:
            # pop-then-handle: several engine loops may run this
            # entry's shim concurrently, and a check-then-pop pair
            # could both pass on one pooled item
            cntl = _pool.pop()
        except IndexError:
            cntl = None
        if cntl is not None:
            meta = cntl.request_meta
            meta.correlation_id = cid
            meta.attachment_size = 0
            meta.timeout_ms = 0
            meta.ici_domain = b""
            cntl.reset_slim(sock.remote_side, sock.id)
        else:
            meta = RpcMeta()
            meta.correlation_id = cid
            meta.service_name = _svc
            meta.method_name = _mth
            cntl = ServerController(meta, sock.remote_side, sock.id,
                                    _send)
        cntl.server = _server
        cntl.begin_time_us = recv_ns // 1000
        cntl._slim_fast = True          # escalations settle recorder-
        #                                 only (no counts were taken)
        if dom is not None:
            sock.ici_peer_domain = dom
            meta.ici_domain = dom
        if nonce is not None and sock.ici_conn_token is None:
            sock.ici_conn_token = nonce
        if tmo is not None:
            meta.timeout_ms = tmo
            _arm(cntl, tmo, recv_ns // 1000)
        na = len(att) if att is not None else 0
        if na:
            meta.attachment_size = na
            ab = IOBuf()
            ab.append_user_data(att)
            cntl._req_att = ab
        span = _pspan(_status.full_name, sock.remote_side)
        if span is not None:
            span.request_size = len(payload) + na
            _backdate(span, recv_ns)
            cntl.span = span
        if tmo is not None and _shed(cntl, "slim",
                                     _status.full_name):
            # doomed work: the budget expired in the native batch —
            # ERPCTIMEDOUT via the classic completion, user code
            # never runs (identical to the chain-bound path)
            cntl.finish(None)
            return None
        try:
            request = parse_payload(payload, _rt)
        except Exception as e:
            cntl.set_failed(Errno.EREQUEST,
                            f"request parse failed: {e}")
            cntl.finish(None)
            return None
        try:
            with _inherit(cntl):
                response = _fn(cntl, request)
        except Exception as e:
            LOG.exception("method %s raised", _status.full_name)
            cntl.set_failed(Errno.EINTERNAL,
                            f"{type(e).__name__}: {e}")
            cntl.finish(None)
            return None
        if cntl.is_async:
            # async escalation OUTLIVES the burst: the "in-flight
            # counts are net-zero for sync items" elision no longer
            # holds — take them now (server gauge, method gauge,
            # '-' tenant slot) so Server.drain()/join() SEE this
            # request and the classic completion settles each
            # symmetrically (operability plane: an invisible async
            # request is one a drain would cut off mid-flight)
            cntl._slim_fast = False
            _server.on_request_in()
            _status.on_requested()
            _server.admission._tenant_acquire("-")
            return None
        if (cntl.failed or cntl._accepted_stream_id
                or cntl.response_compress_type
                or cntl.response_device_attachment is not None
                or not isinstance(response,
                                  (bytes, bytearray, memoryview))):
            cntl.finish(response)
            return None
        if not cntl._mark_finished_if_first():
            return None
        cntl._slim_fast = False
        latency_us = _ns() // 1000 - cntl.begin_time_us
        _status.latency << latency_us
        if cntl._session_data is not None \
                and _server._session_pool is not None:
            _server._session_pool.give_back(cntl._session_data)
            cntl._session_data = None
        ratt = cntl._resp_att
        na_resp = len(ratt) if ratt is not None else 0
        span = cntl.span
        if span is not None:
            span.response_size = len(response) + na_resp
            span.finish(0)
        if na_resp:
            out = (response, ratt.as_contiguous()[0])
        else:
            out = response
        # recycle only a controller NOTHING else references (a
        # handler that stored it keeps it — reuse must never mutate
        # state under a live reference): refs here are the local
        # binding + getrefcount's argument.  The heavy references
        # (attachment views pin engine buffers; spans) are dropped
        # NOW, not at next reuse — an idle pool must not retain
        # request payloads
        if len(_pool) < _SC_POOL_MAX and _refs(cntl) == 2:
            cntl._req_att = None
            cntl._resp_att = None
            cntl.span = None
            _pool.append(cntl)
        return out

    return slim
