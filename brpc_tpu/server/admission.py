"""Overload plane — the ONE admission stage every dispatch path runs.

All five server dispatch paths (classic tpu_std ``rpc_dispatch``, the
slim kind-3 native lane, classic HTTP/1.1, the kind-4 slim HTTP lane,
and gRPC over h2) call :func:`admit` before anything else touches a
request; the stage composes four layers and the lanes only differ in
how they *serialize* a rejection (ELIMIT error frame, HTTP 503 +
``Retry-After``, grpc-status 8 RESOURCE_EXHAUSTED):

1. **server-wide cap** — ``Server.on_request_in``; the cap may be a
   ``make_limiter`` spec ("auto" / "timeout[:ms]" / "constant:N"), so
   the whole server's concurrency adapts to measured latency exactly
   like a per-method limiter (≈ brpc ``-max_concurrency``).
2. **per-method cap** — ``MethodStatus.on_requested``: the existing
   ``AutoLimiter``/``TimeoutLimiter`` plumbing, now fed engine
   CLOCK_MONOTONIC parse-stamp latencies on the native lanes (the slim
   shims anchor ``begin_time_us`` at the frame-parse timestamp, so
   native batch queueing counts — queueing is exactly where an
   overloaded server's latency lives).
3. **CoDel queue discipline** — per-method sojourn time (protocol
   parse stamp → this admission): when sojourn stays above
   ``overload_codel_target_ms`` for a full
   ``overload_codel_interval_ms``, requests are rejected at the head
   BEFORE user code, with the classic CoDel control law
   (``interval/sqrt(n)`` — the interval shrinks under sustained
   overload, so shedding accelerates until the standing queue drains).
   Off by default (``enable_codel_shed``), like brpc's
   ``-server_fail_fast``.
4. **per-tenant weighted fair admission** — tenant identity from meta
   TLV 22 / the ``x-tenant`` header; each tenant's guaranteed share of
   ``tenant_fair_capacity`` is ``weight/active_weight``, and the
   un-guaranteed remainder is a shared free pool — an over-quota hot
   tenant is rejected ONLY while the pool is contended, so a lone
   tenant still gets the whole server ("one hot tenant cannot starve
   the rest").

Every verdict is counted in the module-global
``overload_admission_total{tenant,verdict}`` family (verdicts are a
closed enum — no "unknown" bucket) and live per-tenant concurrency is
exported as ``tenant_inflight{tenant}``; both ride /vars + /metrics,
and the ``/overload`` portal page renders the whole plane.
"""

from __future__ import annotations

import math
import threading
import weakref
from typing import Dict, Optional, Tuple

from ..butil.flags import define_flag, get_flag, watch_flag
from ..butil.status import Errno
from ..butil.time_utils import monotonic_us
from ..bvar.multi_dimension import PassiveDimension

define_flag("enable_codel_shed", False,
            "CoDel queue discipline: reject requests at the head with "
            "ELIMIT when per-method queue sojourn exceeds the target "
            "for a full interval (opt-in, like brpc -server_fail_fast)",
            validator=lambda v: isinstance(v, bool))
define_flag("overload_codel_target_ms", 5.0,
            "CoDel sojourn target: queue delay above this for a full "
            "interval means a standing queue",
            validator=lambda v: isinstance(v, (int, float)) and v >= 0)
define_flag("overload_codel_interval_ms", 100.0,
            "CoDel interval: how long sojourn must stay above target "
            "before head-rejection starts (shrinks as interval/sqrt(n) "
            "under sustained overload)",
            validator=lambda v: isinstance(v, (int, float)) and v >= 0)
define_flag("enable_fair_admission", True,
            "per-tenant weighted fair admission (engages only when the "
            "server configures tenant_fair_capacity); the fairness "
            "bench's A/B switch",
            validator=lambda v: isinstance(v, bool))

_ELIMIT = int(Errno.ELIMIT)
_ELAMEDUCK = int(Errno.ELAMEDUCK)

# the closed verdict enum — every admission decision lands in exactly
# one of these buckets (acceptance: no "unknown" bucket possible)
ADMITTED = "admitted"
SERVER_CAP = "server_cap"
METHOD_CAP = "method_cap"
CODEL = "codel"
TENANT_QUOTA = "tenant_quota"
LAME_DUCK = "lame_duck"
VERDICTS = (ADMITTED, SERVER_CAP, METHOD_CAP, CODEL, TENANT_QUOTA,
            LAME_DUCK)


def normalize_tenant(raw) -> str:
    """One tenant-key normalization for every lane (TLV bytes, header
    bytes/str, ChannelOptions str).  Anonymous traffic pools under
    '-'; values are length-capped — a tenant id is a label, not a
    payload."""
    if not raw:
        return "-"
    if isinstance(raw, (bytes, memoryview)):
        raw = bytes(raw).decode("utf-8", "replace")
    raw = raw.strip()
    return raw[:64] if raw else "-"


# cardinality bound for the per-tenant tables: a client stamping a
# fresh random tenant per request must not grow server memory without
# bound — once a server has seen this many distinct tenants, NEW names
# pool into one overflow bucket (deterministic: known tenants keep
# their own row forever, so acquire/release of one request always
# resolve to the same key)
_MAX_TENANTS = 256
TENANT_OVERFLOW = "~other"


class Rejection:
    """One admission rejection, protocol-agnostic: the lane serializes
    it (``code``/``text`` for tpu_std ELIMIT frames and grpc trailers;
    :func:`http_reject` for both HTTP lanes)."""

    __slots__ = ("reason", "code", "text", "retry_after_s")

    def __init__(self, reason: str, text: str, retry_after_s: int = 1,
                 code: int = _ELIMIT):
        self.reason = reason
        self.code = code
        self.text = text
        self.retry_after_s = retry_after_s


def http_reject(rej: Rejection):
    """The HTTP spelling of an admission rejection, shared by the
    classic bridge and the kind-4 slim shim so the two lanes stay
    byte-identical: (status, body, extra_headers).  ``Retry-After``
    tells well-behaved clients when to come back; ``x-overload-reason``
    distinguishes server-cap / method-cap / codel / tenant-quota."""
    return 503, rej.text.encode(), [
        ("Retry-After", str(rej.retry_after_s)),
        ("x-overload-reason", rej.reason),
        ("x-rpc-error-code", str(rej.code)),
    ]


# ---------------------------------------------------------------------------
# module-global accounting (mirrors deadline.py's shed counters: the
# bvar registry is process-global, so the labeled families aggregate
# across every Server in the process)
# ---------------------------------------------------------------------------

_acct_lock = threading.Lock()
_admission_total: Dict[Tuple[str, str], int] = {}
_controls: "weakref.WeakSet[AdmissionControl]" = weakref.WeakSet()


def _count(tenant: str, verdict: str) -> None:
    with _acct_lock:
        k = (tenant, verdict)
        _admission_total[k] = _admission_total.get(k, 0) + 1


def admission_counters() -> Dict[Tuple[str, str], int]:
    """Snapshot of the per-(tenant, verdict) admission counters."""
    with _acct_lock:
        return dict(_admission_total)


def tenant_inflight_snapshot() -> Dict[str, int]:
    """Live per-tenant in-flight concurrency, aggregated across every
    server in the process (the ``tenant_inflight`` gauge family)."""
    out: Dict[str, int] = {}
    for ctl in list(_controls):
        for t, n in ctl.tenant_inflight().items():
            if n:
                out[t] = out.get(t, 0) + n
    return out


_admission_var = PassiveDimension(
    ("tenant", "verdict"), lambda: admission_counters(),
    name="overload_admission_total")
_inflight_var = PassiveDimension(
    ("tenant",), lambda: tenant_inflight_snapshot(),
    name="tenant_inflight")


# ---------------------------------------------------------------------------
# CoDel state (one per method)
# ---------------------------------------------------------------------------

class _CoDel:
    __slots__ = ("first_above_us", "drop_next_us", "count")

    def __init__(self):
        self.first_above_us = 0     # when sojourn first stayed above
        self.drop_next_us = 0       # next head-drop time while dropping
        self.count = 0              # consecutive drops (control law n)


class AdmissionControl:
    """Per-server admission state: tenant in-flight counters + CoDel
    per-method queue state.  The decision logic lives in
    :meth:`admit`; the verdict counters are module-global."""

    def __init__(self, server):
        self._server = server
        self._lock = threading.Lock()
        self._tenant_inflight: Dict[str, int] = {}
        self._tenant_total = 0
        self._tenant_seen: set = set()     # cardinality registry — ALL
        #                                    observed tenants, admitted
        #                                    OR rejected
        self._codel: Dict[str, _CoDel] = {}
        _controls.add(self)

    # -- introspection (the /overload page) --------------------------------

    def tenant_inflight(self) -> Dict[str, int]:
        with self._lock:
            return {t: n for t, n in self._tenant_inflight.items() if n}

    def _resolve_tenant(self, tenant: str) -> str:
        """Cardinality bound (call under self._lock): known tenants and
        configured weights keep their own row; once _MAX_TENANTS
        distinct names have been OBSERVED — admitted or rejected (a
        flood of rejections with fresh random names is exactly the
        overload case this bound exists for) — new ones pool into
        TENANT_OVERFLOW.  Membership never shrinks, so acquire/release
        and every counter of one request resolve identically."""
        if tenant in self._tenant_seen:
            return tenant
        if len(self._tenant_seen) >= _MAX_TENANTS:
            w = getattr(self._server.options, "tenant_weights", None)
            if not w or tenant not in w:
                return TENANT_OVERFLOW
        self._tenant_seen.add(tenant)
        return tenant

    def codel_state(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {m: {"dropping": int(st.drop_next_us > 0),
                        "drops": st.count}
                    for m, st in self._codel.items()}

    # -- fair admission ----------------------------------------------------

    def _fair_capacity(self) -> int:
        cap = getattr(self._server.options, "tenant_fair_capacity", 0)
        return cap if isinstance(cap, int) and cap > 0 else 0

    def _tenant_weight(self, tenant: str) -> float:
        w = getattr(self._server.options, "tenant_weights", None)
        if not w:
            return 1.0
        return max(0.001, float(w.get(tenant, 1)))

    def _tenant_acquire(self, tenant: str) -> bool:
        """Weighted quota + shared free pool, under one lock.  A tenant
        below its guaranteed share is ALWAYS admitted (the guarantee);
        above it, admission needs free capacity (total < capacity) —
        so an over-quota hot tenant is rejected only while contended."""
        cap = self._fair_capacity()
        with self._lock:
            tenant = self._resolve_tenant(tenant)
            if not cap or not get_flag("enable_fair_admission", True):
                # accounting only (the tenant_inflight gauge stays
                # truthful even with fairness off — the bench A/B
                # relies on it)
                self._tenant_inflight[tenant] = \
                    self._tenant_inflight.get(tenant, 0) + 1
                self._tenant_total += 1
                return True
            mine = self._tenant_inflight.get(tenant, 0)
            if mine > 0:
                active_w = sum(self._tenant_weight(t)
                               for t, n in self._tenant_inflight.items()
                               if n > 0)
            else:
                active_w = self._tenant_weight(tenant) + sum(
                    self._tenant_weight(t)
                    for t, n in self._tenant_inflight.items() if n > 0)
            guarantee = max(1, int(cap * self._tenant_weight(tenant)
                                   / max(active_w, 0.001)))
            if mine >= guarantee and self._tenant_total >= cap:
                return False
            self._tenant_inflight[tenant] = mine + 1
            self._tenant_total += 1
            return True

    def release(self, tenant_raw) -> None:
        """Settle one admitted request's tenant slot (every lane's
        completion path calls this through ``Server.on_request_out``)."""
        tenant = normalize_tenant(tenant_raw)
        with self._lock:
            tenant = self._resolve_tenant(tenant)
            n = self._tenant_inflight.get(tenant, 0)
            if n > 0:
                self._tenant_inflight[tenant] = n - 1
                self._tenant_total -= 1

    # -- CoDel -------------------------------------------------------------

    def _codel_drop(self, method: str, sojourn_us: float,
                    now_us: int) -> bool:
        target_us = float(get_flag("overload_codel_target_ms", 5.0)) * 1000
        interval_us = float(get_flag("overload_codel_interval_ms",
                                     100.0)) * 1000
        with self._lock:
            st = self._codel.get(method)
            if st is None:
                st = self._codel[method] = _CoDel()
            if sojourn_us <= target_us:
                # queue drained below target: leave dropping state
                st.first_above_us = 0
                st.drop_next_us = 0
                st.count = 0
                return False
            if st.first_above_us == 0:
                # first above-target observation: arm the interval
                st.first_above_us = now_us + int(interval_us)
                return False
            if now_us < st.first_above_us:
                return False            # not above-target long enough yet
            # standing queue: head-drop on the CoDel control law —
            # interval/sqrt(n) between drops, accelerating under
            # sustained overload until sojourn falls below target
            if st.drop_next_us and now_us < st.drop_next_us:
                return False
            st.count += 1
            st.drop_next_us = now_us + max(
                1, int(interval_us / math.sqrt(st.count)))
            return True

    # -- the one admission decision ----------------------------------------

    def admit(self, entry, lane: str, tenant_raw,
              arrival_us: Optional[int]) -> Optional[Rejection]:
        """Run the four admission layers for one request.  None =
        admitted (server + method in-flight taken, tenant slot held —
        the lane MUST route its completion through
        ``MethodStatus.on_responded`` + ``Server.on_request_out(tenant=
        ...)``).  A :class:`Rejection` = answer the client NOW, before
        user code; all taken counts are already undone."""
        server = self._server
        status = entry.status
        with self._lock:
            tenant = self._resolve_tenant(normalize_tenant(tenant_raw))
        if getattr(server, "draining", False):
            # operability plane, layer 0: a draining server admits
            # NOTHING new — the in-flight set must reach zero within
            # the grace.  ELAMEDUCK (not ELIMIT): the client removes
            # the node from LB selection with no breaker penalty and
            # fail-fast-retries elsewhere; every lane serializes this
            # through its existing rejection path.
            _count(tenant, LAME_DUCK)
            return Rejection(LAME_DUCK, "server draining (lame duck)",
                             code=_ELAMEDUCK)
        if not server.on_request_in():
            _count(tenant, SERVER_CAP)
            return Rejection(SERVER_CAP, "server max_concurrency")
        if getattr(server, "draining", False):
            # drain-start raced the unlocked check above: our in-flight
            # increment is now VISIBLE to drain's settle wait (it reads
            # under the same lock), so undo and reject — the handler
            # must not start against a server about to tear down
            server.on_request_out()
            _count(tenant, LAME_DUCK)
            return Rejection(LAME_DUCK, "server draining (lame duck)",
                             code=_ELAMEDUCK)
        if not status.on_requested():
            server.on_request_out()
            _count(tenant, METHOD_CAP)
            # the live limit rides along so a fail-fast client's log
            # says WHAT it bounced off, not just that it bounced
            return Rejection(
                METHOD_CAP,
                f"method max_concurrency ({status.full_name} at "
                f"{status.live_max_concurrency()})")
        if arrival_us and get_flag("enable_codel_shed", False):
            now = monotonic_us()
            if self._codel_drop(status.full_name,
                                now - arrival_us, now):
                status.undo_requested()
                server.on_request_out()
                _count(tenant, CODEL)
                return Rejection(
                    CODEL, f"{status.full_name} codel queue delay over "
                           "target (standing queue shed)")
        if not self._tenant_acquire(tenant):
            status.undo_requested()
            server.on_request_out()
            _count(tenant, TENANT_QUOTA)
            return Rejection(TENANT_QUOTA,
                             f"tenant {tenant} quota exceeded")
        _count(tenant, ADMITTED)
        return None


def admit(server, entry, lane: str, tenant_raw,
          arrival_us: Optional[int]) -> Optional[Rejection]:
    """Module-level convenience: every lane calls this one function."""
    return server.admission.admit(entry, lane, tenant_raw, arrival_us)


# ---------------------------------------------------------------------------
# Trivial-shape fast admission (the slim lanes' hot path).  When NO
# admission layer is configured — no server cap/limiter, no method
# cap/limiter, CoDel off, no fair capacity — and the request carries no
# tenant, the full admit() walk is pure overhead: the decision is known
# to be ADMITTED before it starts.  fast_in/fast_out keep every counter
# truthful (server/method in-flight gauges, the '-' tenant gauge, the
# admitted-verdict bucket) while skipping the decision machinery.  The
# CoDel flag is cached through a watcher so the per-call check is one
# list read, not a flags-table lookup.
# ---------------------------------------------------------------------------

_codel_live = [bool(get_flag("enable_codel_shed", False))]
watch_flag("enable_codel_shed",
           lambda v: _codel_live.__setitem__(0, bool(v)))


def trivial_shape(server, status) -> bool:
    """True when admission for an untenanted request on this method is
    decision-free (all four layers unconfigured).  Reads live state, so
    caps installed mid-run are honored on the very next call."""
    if status.limiter is not None or status.max_concurrency:
        return False
    if _codel_live[0]:
        return False
    if server.draining:
        # drain: every request must take the full admit() walk so the
        # lame-duck rejection (and its verdict accounting) fires
        return False
    opts = server.options
    mc = opts.max_concurrency
    if not isinstance(mc, int) or mc > 0:
        return False
    cap = getattr(opts, "tenant_fair_capacity", 0)
    return not (isinstance(cap, int) and cap > 0)


def count_admitted_burst(n: int) -> None:
    """Fold one burst's worth of trivial-shape admitted verdicts into
    the module-global counter family: one lock hold per BURST instead
    of one per item (the ISSUE-8 per-burst-aggregate discipline; the
    verdict enum stays closed — every fast item still lands in exactly
    one bucket)."""
    if n <= 0:
        return
    with _acct_lock:
        k = ("-", ADMITTED)
        _admission_total[k] = _admission_total.get(k, 0) + n
