"""Server — lifecycle + service registry.

Capability parity with /root/reference/src/brpc/server.cpp:746 (StartInternal),
:464 (AddBuiltinServices), server.h:59 (ServerOptions). Differences by
design: protocols already live in a process-global registry, so building
the acceptor's handler table is collecting every server-capable protocol;
worker sizing configures the fiber runtime.
"""

from __future__ import annotations

import os as _os
import socket as _socket
import threading
import time as _time
import weakref as _weakref
from typing import Any, Callable, Dict, Optional, Tuple

from ..butil.endpoint import EndPoint, parse_endpoint
from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..bvar.passive_status import PassiveStatus
from ..fiber import runtime as fiber_runtime
from ..protocol.base import list_protocols
from ..transport.acceptor import Acceptor
from ..transport.event_dispatcher import global_dispatcher
from ..transport.input_messenger import InputMessenger
from .method_status import MethodStatus
from .service import extract_methods, service_name_of

# -- operability plane (graceful drain / lame duck / hot restart) -----------

define_flag("drain_grace_ms", 5000,
            "graceful-drain grace: how long Server.drain() (and a "
            "post-stop join()) waits for in-flight requests, staged "
            "shm slots and client-demux entries to settle before "
            "force-closing stragglers with the named reason "
            "'drain_grace_expired'",
            validator=lambda v: isinstance(v, int) and v > 0)
define_flag("enable_lame_duck", True,
            "emit the lame-duck drain signal to connected peers while "
            "draining (tpu_std meta TLV 23 — natively on the engine "
            "lanes too — plus x-lame-duck/Connection: close on HTTP "
            "and GOAWAY on h2): clients re-resolve immediately with "
            "no breaker penalty.  Off = drain still rejects new work "
            "(ELAMEDUCK) but peers only learn per-rejection",
            validator=lambda v: isinstance(v, bool))

define_flag("graceful_quit_on_sigterm", False,
            "install a SIGTERM handler that drains every live server "
            "(unpublish, lame-duck, bounded in-flight + stream settle) "
            "and then stops it — the brpc -graceful_quit_on_sigterm "
            "shape.  Read at Server.start(); the handler can only "
            "install from the main thread",
            validator=lambda v: isinstance(v, bool))

# drain phases (ints so the bvar graphs): the names ride /status
DRAIN_SERVING, DRAIN_DRAINING, DRAIN_STOPPED = 0, 1, 2
_DRAIN_PHASE_NAMES = ("serving", "draining", "stopped")
# the named force-close reason at grace expiry (pinned by the check
# tooling's reason discipline: a force-closed connection's error text
# says WHY, not just that it died)
DRAIN_FORCE_CLOSE_REASON = "drain_grace_expired"

_live_servers: "_weakref.WeakSet[Server]" = _weakref.WeakSet()

_sigterm_installed = False


def _install_sigterm_drain() -> None:
    """Signal-driven drain (``-graceful_quit_on_sigterm``): SIGTERM →
    ``drain()`` then ``stop()`` on EVERY live server.  The handler only
    spawns a worker thread (signal context must stay tiny); the worker
    runs the normal grace-bounded drain, so in-flight requests finish
    and streams close with the named lame-duck reason.  A serving
    process parked in ``run_until_asked_to_quit()``/``join()`` then
    returns from main and exits client-invisibly; an embedder doing
    other work keeps running (we drain ITS servers, not its process).
    A SECOND SIGTERM while/after draining restores the default
    disposition and re-delivers — terminate now, gracefully-degraded —
    so supervisors escalating before SIGKILL still get a clean death.
    Installable from the main thread only (CPython restriction) —
    elsewhere it degrades to a warning."""
    global _sigterm_installed
    if _sigterm_installed:
        return
    import signal as _signal

    _drain_started = [False]

    def _on_sigterm(_signum, _frame):
        if _drain_started[0]:
            # second TERM: the operator wants OUT — default disposition
            # (handlers run on the main thread, so re-arming is legal)
            _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
            _os.kill(_os.getpid(), _signal.SIGTERM)
            return
        _drain_started[0] = True

        def _drain_all():
            for s in list(_live_servers):
                if not s._started:
                    continue
                # per-server isolation: one replica's drain failure
                # must not leave the REST of the process serving after
                # SIGTERM (the supervisor would escalate to SIGKILL)
                try:
                    s.drain()
                except Exception:
                    LOG.exception("sigterm drain failed for %s",
                                  s._listen_endpoint)
                finally:
                    try:
                        s.stop()
                    except Exception:
                        LOG.exception("sigterm stop failed for %s",
                                      s._listen_endpoint)

        threading.Thread(target=_drain_all, name="sigterm-drain",
                         daemon=True).start()

    try:
        _signal.signal(_signal.SIGTERM, _on_sigterm)
        _sigterm_installed = True
    except ValueError:
        LOG.warning("graceful_quit_on_sigterm: not on the main "
                    "thread; SIGTERM handler not installed")


def _drain_state_now() -> int:
    """Max drain phase across LIVE (started) servers — any draining
    server shows; fully-stopped ones drop out so the gauge returns to
    0 once the process serves nothing mid-restart."""
    st = DRAIN_SERVING
    for s in list(_live_servers):
        if s._started:
            st = max(st, s._drain_state)
    return st


def _drain_inflight_now() -> int:
    """In-flight requests still settling on DRAINING servers (0 when
    nothing drains — the rolling-restart dashboards watch this fall)."""
    n = 0
    for s in list(_live_servers):
        if s._drain_state == DRAIN_DRAINING:
            n += s._inflight
    return n


_drain_state_var = PassiveStatus(_drain_state_now,
                                 name="server_drain_state")
_drain_inflight_var = PassiveStatus(_drain_inflight_now,
                                    name="drain_inflight_remaining")


def _ensure_drain_vars() -> None:
    """Import-time bvars don't survive a test-scoped registry wipe
    (bvar ``clear_registry_for_tests``): re-expose at every Server
    construction — two dict reads when nothing changed."""
    from ..bvar.variable import find_exposed
    for name, var in (("server_drain_state", _drain_state_var),
                      ("drain_inflight_remaining",
                       _drain_inflight_var)):
        if find_exposed(name) is not var:
            var.expose(name)


def _publish_file_edit(path: str, line: str, add: bool) -> None:
    """Atomically add/remove one server line in a file-NS list (the
    ``file://`` naming source): read-modify-replace under an flock so
    replicas publishing while a draining neighbor unpublishes cannot
    lose each other's lines."""
    import fcntl
    lockp = path + ".lock"
    with open(lockp, "a+") as lk:
        fcntl.flock(lk.fileno(), fcntl.LOCK_EX)
        try:
            try:
                with open(path) as f:
                    lines = [ln.strip() for ln in f if ln.strip()]
            except FileNotFoundError:
                lines = []
            if add:
                if line not in lines:
                    lines.append(line)
            else:
                lines = [ln for ln in lines if ln != line]
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write("".join(ln + "\n" for ln in lines))
            _os.replace(tmp, path)
        finally:
            fcntl.flock(lk.fileno(), fcntl.LOCK_UN)


class ServerOptions:
    """≈ ServerOptions (server.h:59). Only capabilities the TPU build has
    wired so far; grows with the build."""

    __slots__ = ("num_workers", "max_concurrency", "method_max_concurrency",
                 "auth", "interceptor", "idle_timeout_s",
                 "internal_port", "server_info_name",
                 "native", "native_loops", "usercode_inline",
                 "ssl_cert", "ssl_key", "ssl_context",
                 "restful_mappings", "session_local_data_factory",
                 "tenant_fair_capacity", "tenant_weights", "reuse_port")

    def __init__(self):
        self.num_workers = 0            # 0 = leave fiber runtime defaults
        # server-wide in-flight cap: an int (0 = off), OR a make_limiter
        # spec ("auto" / "timeout[:ms]" / "constant:N") / a
        # ConcurrencyLimiter instance — the whole server's admission
        # then adapts to measured latency (overload plane, ≈ brpc
        # -max_concurrency taking AdaptiveMaxConcurrency)
        self.max_concurrency: Any = 0
        # "Service.Method" -> int cap, "auto", "constant:N", or a
        # ConcurrencyLimiter instance; the "*" key is the default spec
        # applied to every method without its own entry
        self.method_max_concurrency: Dict[str, Any] = {}
        # overload plane, per-tenant fair admission: total concurrency
        # the tenant scheduler divides (0 = tenant layer accounts but
        # never rejects).  Weighted guaranteed shares come from
        # tenant_weights (default weight 1); capacity beyond the
        # guarantees is a shared free pool.
        self.tenant_fair_capacity = 0
        self.tenant_weights: Dict[str, float] = {}
        self.auth: Optional[Any] = None          # .verify(auth_data, cntl)
        self.interceptor: Optional[Callable] = None  # (cntl) -> (ok, code, text)
        self.idle_timeout_s = -1
        self.internal_port = -1
        self.server_info_name = ""
        # serve the main port through the native C++ IO engine (framed
        # protocols only; pair with internal_port for the HTTP portal).
        # Falls back to the Python transport if the engine can't build.
        self.native = False
        # 0 = placement-aware auto (one loop per core up to 4 — see
        # native_bridge.default_engine_loops); explicit values pin it
        self.native_loops = 0
        # run user code directly on the native engine's IO thread instead
        # of a fiber (≈ the reference's usercode_in_pthread,
        # /root/reference/src/brpc/details/usercode_backup_pool.h): saves a
        # thread handoff per request — the echo-class latency fast path.
        # Only enable when handlers never block (or begin_async() early).
        self.usercode_inline = False
        # TLS on the serving port (≈ ServerSSLOptions,
        # /root/reference/src/brpc/ssl_options.h:83): set cert+key paths,
        # or a ready ssl.SSLContext.  TLS serves through the Python
        # transport (the native engine speaks cleartext framed protocols).
        self.ssl_cert = ""
        self.ssl_key = ""
        self.ssl_context = None
        # restful routing (≈ restful.cpp): "PATH => Service.Method" pairs,
        # comma separated; a trailing /* captures the rest of the path
        # into cntl.http_unresolved_path.
        #   "/v1/echo => E.Echo, /files/* => Files.Get"
        self.restful_mappings = ""
        # SimpleDataPool factory (≈ simple_data_pool.h): per-request
        # reusable user data via cntl.session_local_data()
        self.session_local_data_factory = None
        # hot restart, overlap-start flavor: bind the listener with
        # SO_REUSEPORT even outside the native sharded-accept case, so
        # a successor process can bind the SAME port while this one
        # drains (the kernel splits accepts; the lame-duck signal
        # steers clients to the successor).  Costs the EADDRINUSE
        # safety against unrelated same-UID processes — off by default.
        self.reuse_port = False


class _MethodEntry:
    __slots__ = ("fn", "request_type", "status", "service", "method_name",
                 "grpc_streaming", "raw_fn", "native_kind", "chain")

    def __init__(self, fn, request_type, status, service, method_name,
                 grpc_streaming=False, raw_fn=None, native_kind=None):
        self.fn = fn
        self.request_type = request_type
        self.grpc_streaming = grpc_streaming
        self.status = status
        self.service = service
        self.method_name = method_name
        self.raw_fn = raw_fn     # bytes-in/bytes-out latency-lane handler
        self.native_kind = native_kind   # C++ semantic ("echo"/"const")
        self.chain = None   # lazily-compiled tpu_std interceptor chain


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Any] = {}
        self._methods: Dict[Tuple[str, str], _MethodEntry] = {}
        self._listener: Optional[_socket.socket] = None
        self._acceptor: Optional[Acceptor] = None
        self._native_bridge = None
        self._internal_acceptor: Optional[Acceptor] = None
        self._internal_endpoint: Optional[EndPoint] = None
        self._messenger: Optional[InputMessenger] = None
        self._listen_endpoint: Optional[EndPoint] = None
        self._started = False
        self._stopped_event = threading.Event()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # operability plane: drain state machine + in-flight settle
        # rendezvous (the condition SHARES the in-flight lock, so
        # on_request_out's decrement and the notify are one critical
        # section)
        self._drain_state = DRAIN_SERVING
        self._drain_cv = threading.Condition(self._inflight_lock)
        self._drain_deadline_mono = 0.0
        self._drain_force_closed = 0
        self._published: Optional[Tuple[str, str]] = None
        self._inherited_listener = False   # hot restart: fd came from
        #                                    a predecessor, not bind()
        _live_servers.add(self)
        _ensure_drain_vars()
        self.version = ""
        self._restful = []           # parsed (segments, has_rest, entry_key)
        self._session_pool = None    # SimpleDataPool when factory set
        self._admission = None       # lazy AdmissionControl (overload plane)
        self._server_limiter = None  # adaptive server-wide cap (spec'd
        self._server_limiter_spec = None   # max_concurrency), parsed lazily

    # -- registry ----------------------------------------------------------

    def add_service(self, service: Any, name: str = "") -> int:
        """≈ Server::AddService. Method set is extracted by reflection;
        per-method request types come from the @method decorator."""
        if self._started:
            LOG.error("add_service after start")
            return -1
        sname = name or service_name_of(service)
        if sname in self._services:
            LOG.error("service %s already added", sname)
            return -1
        if sname == "redis" and hasattr(service, "on_command"):
            # RESP service: the shared port speaks redis to it
            # (≈ ServerOptions.redis_service, src/brpc/redis.h)
            self._services[sname] = service
            return 0
        if sname == "thrift" and hasattr(service, "handle"):
            # thrift framed-binary service on the shared port
            self._services[sname] = service
            return 0
        methods = extract_methods(service)
        if not methods:
            LOG.error("service %s has no public methods", sname)
            return -1
        self._services[sname] = service
        from ..policy.concurrency_limiter import (ConcurrencyLimiter,
                                                  make_limiter)
        default_mc = self.options.method_max_concurrency.get("*", 0)
        if isinstance(default_mc, ConcurrencyLimiter):
            # one INSTANCE as the default would be shared by reference
            # across every method — mixed latencies feeding one
            # adaptive state make the limit meaningless for all of
            # them.  Spec strings get a fresh limiter per method.
            LOG.error("method_max_concurrency['*'] must be a spec "
                      "(e.g. \"auto\"), not a limiter instance")
            del self._services[sname]
            return -1
        for mname, fn in methods.items():
            full = f"{sname}.{mname}"
            mc = self.options.method_max_concurrency.get(full, default_mc)
            limiter = None
            if isinstance(mc, ConcurrencyLimiter):
                limiter, mc = mc, 0
            elif isinstance(mc, str):
                limiter = make_limiter(mc)
                mc = 0
            status = MethodStatus(full, max_concurrency=mc, limiter=limiter)
            entry = _MethodEntry(
                fn=fn,
                request_type=getattr(fn, "_rpc_request_type", None),
                status=status,
                service=service,
                method_name=mname,
                grpc_streaming=getattr(fn, "_grpc_streaming", False),
                raw_fn=fn if getattr(fn, "_rpc_raw", False) else None,
                native_kind=getattr(fn, "_rpc_native", None),
            )
            self._methods[(sname, mname)] = entry
        return 0

    def find_method(self, service_name: str,
                    method_name: str) -> Optional[_MethodEntry]:
        return self._methods.get((service_name, method_name))

    def find_restful(self, parts) -> Optional[Tuple[_MethodEntry, str]]:
        """Match an HTTP path against restful_mappings
        (≈ /root/reference/src/brpc/restful.cpp pattern table).
        Returns (entry, unresolved_path) or None."""
        for segs, has_rest, key in self._restful:
            n = len(segs)
            if has_rest:
                if len(parts) < n or parts[:n] != segs:
                    continue
                entry = self._methods.get(key)
                if entry is not None:
                    return entry, "/".join(parts[n:])
            elif list(parts) == segs:
                entry = self._methods.get(key)
                if entry is not None:
                    return entry, ""
        return None

    def _parse_restful(self) -> None:
        self._restful = []
        spec = self.options.restful_mappings or ""
        for pair in spec.split(","):
            pair = pair.strip()
            if not pair:
                continue
            try:
                pattern, _, target = pair.partition("=>")
                pattern = pattern.strip()
                svc, _, mth = target.strip().rpartition(".")
                segs = [p for p in pattern.split("/") if p]
                has_rest = bool(segs) and segs[-1] == "*"
                if has_rest:
                    segs = segs[:-1]
                if (svc, mth) not in self._methods:
                    LOG.error("restful mapping %r: unknown method %s.%s",
                              pair, svc, mth)
                    continue
                self._restful.append((segs, has_rest, (svc, mth)))
            except ValueError:
                LOG.error("bad restful mapping %r", pair)
        # longest (most specific) patterns first; exact beats wildcard
        # at equal length
        self._restful.sort(key=lambda t: (-len(t[0]), t[1]))

    @property
    def services(self) -> Dict[str, Any]:
        return dict(self._services)

    @property
    def methods(self):
        return self._methods

    # -- server-wide concurrency + admission (overload plane) -------------

    @property
    def admission(self):
        """This server's AdmissionControl (lazy) — the ONE admission
        stage all five dispatch paths run (server/admission.py)."""
        ctl = self._admission
        if ctl is None:
            from .admission import AdmissionControl
            with self._inflight_lock:
                if self._admission is None:
                    self._admission = AdmissionControl(self)
                ctl = self._admission
        return ctl

    def server_limiter(self):
        """The adaptive server-wide concurrency limiter when
        ``options.max_concurrency`` is a spec/instance (None for the
        classic int cap).  Parsed lazily and re-parsed when the option
        object changes, so tests/operators may set it any time before
        traffic."""
        mc = self.options.max_concurrency
        if isinstance(mc, int):
            return None
        if mc is not self._server_limiter_spec:
            from ..policy.concurrency_limiter import (ConcurrencyLimiter,
                                                      make_limiter)
            self._server_limiter = mc if isinstance(mc, ConcurrencyLimiter) \
                else make_limiter(mc)
            self._server_limiter_spec = mc
        return self._server_limiter

    def on_request_in(self) -> bool:
        lim = self.server_limiter()
        if lim is not None:
            limit = lim.max_concurrency()
        else:
            limit = self.options.max_concurrency
        with self._inflight_lock:
            if limit > 0 and self._inflight >= limit:
                return False
            self._inflight += 1
            return True

    def on_request_out(self, tenant=None, error_code: int = 0,
                       latency_us: float = 0.0) -> None:
        """Settle one admitted request.  The five dispatch lanes pass
        the request's tenant (fair-admission slot release) and the
        measured outcome (the adaptive server-wide limiter's feed);
        legacy/error paths may still call it bare."""
        with self._inflight_lock:
            if self._inflight > 0:
                self._inflight -= 1
            if self._inflight == 0:
                # drain()/join() block on this rendezvous: the LAST
                # settling request wakes them
                self._drain_cv.notify_all()
        if error_code or latency_us:
            lim = self._server_limiter
            if lim is not None:
                lim.on_responded(error_code, latency_us)
        if tenant is not None and self._admission is not None:
            self._admission.release(tenant)

    @property
    def inflight(self) -> int:
        return self._inflight

    def _server_ssl_context(self):
        opts = self.options
        if opts.ssl_context is not None:
            return opts.ssl_context
        if not opts.ssl_cert:
            return None
        import ssl as _ssl
        ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(opts.ssl_cert, opts.ssl_key or None)
        return ctx

    # -- lifecycle ---------------------------------------------------------

    def start(self, addr: Any = "127.0.0.1:0",
              inherit_from: Optional[str] = None) -> int:
        """≈ Server::Start. ``addr`` is "ip:port" (port 0 = ephemeral),
        an EndPoint, or a bare port int.  ``inherit_from`` names a
        predecessor's hot-restart handoff socket (see
        :meth:`export_listeners`): the listener fds — kernel listen
        queue included — are taken over instead of bound fresh, so a
        binary swap never refuses a connect."""
        if self._started:
            return -1
        if isinstance(addr, int):
            ep = EndPoint(host="0.0.0.0", port=addr)
        elif isinstance(addr, EndPoint):
            ep = addr
        else:
            ep = parse_endpoint(str(addr))
        if self.options.num_workers > 0:
            fiber_runtime.set_concurrency(self.options.num_workers)
        if bool(get_flag("graceful_quit_on_sigterm", False)):
            # signal-driven drain: SIGTERM → grace-bounded drain + stop
            _install_sigterm_drain()

        inherited_extras = []
        if inherit_from:
            from . import hot_restart as _hot_restart
            try:
                got = _hot_restart.import_listeners(inherit_from)
            except (OSError, ValueError) as e:
                LOG.error("hot-restart import from %s failed: %s",
                          inherit_from, e)
                return -1
            # primary = the inherited listener matching the requested
            # port (any, when the caller asked for an ephemeral one);
            # the rest become the engine's shard listeners
            lst = None
            for s, _h, p in got:
                if lst is None and ep.port in (0, p):
                    lst = s
                else:
                    inherited_extras.append(s)
            if lst is None:
                for s, _h, _p in got:
                    s.close()
                LOG.error("hot-restart handoff carried no listener "
                          "for port %d", ep.port)
                return -1
            self._inherited_listener = True
        else:
            lst = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            lst.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            want_reuseport = bool(self.options.reuse_port) \
                and hasattr(_socket, "SO_REUSEPORT")
            if self.options.native and hasattr(_socket, "SO_REUSEPORT"):
                # the native bridge shards accept across its loops with
                # one SO_REUSEPORT listener per loop; the PRIMARY socket
                # must carry the option from before bind or the kernel
                # refuses the shard binds (mixed-mode).  Gated on the
                # flag AND a multi-loop resolution: REUSEPORT also
                # waives EADDRINUSE against other same-UID processes,
                # so a server that will never shard must not pay that
                # safety loss.  ``options.reuse_port`` opts in anyway —
                # the hot-restart overlap-start story.
                from ..butil.flags import get_flag as _get_flag
                from ..transport.native_bridge import default_engine_loops
                nloops = self.options.native_loops \
                    or default_engine_loops()
                if nloops > 1 and bool(_get_flag("engine_reuseport",
                                                 True)):
                    want_reuseport = True
            if want_reuseport:
                try:
                    lst.setsockopt(_socket.SOL_SOCKET,
                                   _socket.SO_REUSEPORT, 1)
                except OSError:
                    pass
            try:
                lst.bind(ep.to_sockaddr())
            except OSError as e:
                LOG.error("bind %s: %s", ep, e)
                lst.close()
                return -1
            lst.listen(1024)
        host, port = lst.getsockname()[:2]
        self._listen_endpoint = EndPoint(host=host, port=port)
        self._listener = lst

        if self.options.restful_mappings:
            self._parse_restful()
        if self.options.session_local_data_factory is not None:
            from ..butil.simple_data_pool import SimpleDataPool
            self._session_pool = SimpleDataPool(
                self.options.session_local_data_factory)
        # handler table = every registered server-capable protocol
        # (≈ Server::BuildAcceptor collecting protocols, server.cpp:572);
        # importing the modules registers the builtins
        from ..ici import endpoint as _ici        # noqa: F401
        from ..protocol import h2_rpc as _h2      # noqa: F401
        from ..protocol import http as _http      # noqa: F401
        from ..protocol import resp as _resp      # noqa: F401
        from ..protocol import streaming as _str  # noqa: F401
        from ..protocol import thrift_proto as _t  # noqa: F401
        from ..protocol import tpu_std as _tpu    # noqa: F401
        handlers = [p for p in list_protocols() if p.support_server]
        self._messenger = InputMessenger(handlers, arg=self)
        ssl_ctx = self._server_ssl_context()
        if self.options.native and ssl_ctx is None:
            from ..native import load as load_native
            native_mod = load_native()
            if native_mod is not None:
                from ..transport.native_bridge import NativeBridge
                self._native_bridge = NativeBridge(
                    self, native_mod, loops=self.options.native_loops)
                self._native_bridge.listen(
                    lst, inherited_shards=inherited_extras or None)
                inherited_extras = []
            else:
                LOG.warning("native engine unavailable; serving %s through "
                            "the Python transport", ep)
        elif self.options.native and ssl_ctx is not None:
            LOG.warning("TLS serving uses the Python transport; "
                        "native engine disabled for %s", ep)
        if self._native_bridge is None:
            self._acceptor = Acceptor(self._messenger, ssl_context=ssl_ctx)
            self._acceptor.start_accept(lst)
        if inherited_extras:
            # inherited shard listeners with no native engine to serve
            # them: close rather than strand their queues silently
            LOG.warning("closing %d inherited shard listener(s) the "
                        "Python transport cannot serve",
                        len(inherited_extras))
            for s in inherited_extras:
                s.close()

        # Optional second, operator-only port: builtin portal pages (flag
        # mutation, rpcz, profilers …) are served ONLY to connections
        # accepted here when set (≈ server.cpp:1079-1086).
        if self.options.internal_port >= 0:
            ilst = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            ilst.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            try:
                ilst.bind((host, self.options.internal_port))
            except OSError as e:
                LOG.error("bind internal port %d: %s",
                          self.options.internal_port, e)
                ilst.close()
                self._acceptor.stop_accept()
                self._acceptor = None
                self._messenger = None
                self._listener = None
                self._listen_endpoint = None
                return -1
            ilst.listen(128)
            self._internal_endpoint = EndPoint(host=host,
                                               port=ilst.getsockname()[1])
            self._internal_acceptor = Acceptor(self._messenger,
                                               tag="internal")
            self._internal_acceptor.start_accept(ilst)
        self._started = True
        self._drain_state = DRAIN_SERVING
        self._drain_force_closed = 0
        self._stopped_event.clear()
        from ..bvar.dump import ensure_dumper
        ensure_dumper()     # no-op unless the bvar_dump flag is on
        from .. import fleet as _fleet
        _fleet.on_server_start(self)    # flight recorder: restart event
        LOG.info("Server started at %s (%d services, %d methods)",
                 self._listen_endpoint, len(self._services),
                 len(self._methods))
        return 0

    @property
    def listen_endpoint(self) -> Optional[EndPoint]:
        return self._listen_endpoint

    @property
    def internal_endpoint(self) -> Optional[EndPoint]:
        return self._internal_endpoint

    @property
    def running(self) -> bool:
        return self._started

    def connection_count(self) -> int:
        n = self._acceptor.connection_count() if self._acceptor else 0
        if self._native_bridge is not None:
            n += self._native_bridge.connection_count()
        if self._internal_acceptor is not None:
            n += self._internal_acceptor.connection_count()
        return n

    # -- operability plane: drain / lame duck ------------------------------

    @property
    def draining(self) -> bool:
        return self._drain_state == DRAIN_DRAINING

    @property
    def drain_phase(self) -> str:
        return _DRAIN_PHASE_NAMES[self._drain_state]

    @property
    def lame_duck_signal_on(self) -> bool:
        """True while responses should carry the lame-duck signal."""
        return self._drain_state == DRAIN_DRAINING \
            and bool(get_flag("enable_lame_duck", True))

    @property
    def drain_force_closed(self) -> int:
        return self._drain_force_closed

    def publish(self, target: str) -> int:
        """Register this server's address with a naming source — the
        ``file://`` scheme (one ``host:port`` per line, exactly what
        ``FileNamingService`` reads): the fleet-membership half of the
        rolling-restart story.  ``drain()`` unpublishes first, so new
        clients stop resolving here before the lame-duck signal even
        lands on connected ones."""
        if self._listen_endpoint is None:
            return -1
        path = target[len("file://"):] if target.startswith("file://") \
            else target
        line = f"{self._listen_endpoint.host}:{self._listen_endpoint.port}"
        try:
            _publish_file_edit(path, line, add=True)
        except OSError as e:
            LOG.error("publish to %s failed: %s", path, e)
            return -1
        self._published = (path, line)
        return 0

    def unpublish(self) -> None:
        pub = self._published
        if pub is None:
            return
        self._published = None
        path, line = pub
        try:
            _publish_file_edit(path, line, add=False)
        except OSError as e:
            LOG.warning("unpublish from %s failed: %s", path, e)

    def _wait_inflight_zero(self, deadline_mono: float) -> bool:
        with self._inflight_lock:
            while self._inflight > 0:
                left = deadline_mono - _time.monotonic()
                if left <= 0:
                    return False
                self._drain_cv.wait(min(left, 0.05))
            return True

    def _force_close_stragglers(self) -> int:
        """Grace expired: force-close connections still carrying work,
        each with the NAMED reason — a client sees a precise error, an
        operator sees a counted event, never a silent hang."""
        n = 0
        if self._acceptor is not None:
            for s in self._acceptor.live_sockets():
                s.set_failed(Errno.ELOGOFF, DRAIN_FORCE_CLOSE_REASON)
                s.release()
                n += 1
        if self._native_bridge is not None:
            n += self._native_bridge.force_close_all(
                DRAIN_FORCE_CLOSE_REASON)
        self._drain_force_closed += n
        if n:
            LOG.warning("drain grace expired: force-closed %d "
                        "connection(s) (%s)", n, DRAIN_FORCE_CLOSE_REASON)
        return n

    def export_listeners(self, path: str,
                         timeout_s: float = 30.0) -> int:
        """Hot restart, predecessor side: serve ONE fd handoff at
        unix-socket ``path`` (blocking, bounded by ``timeout_s`` —
        run it on a thread while still serving), shipping the bound
        listener fds (primary + SO_REUSEPORT shards) to the successor
        binary.  Then :meth:`drain` + :meth:`stop`: established
        connections finish HERE; everything queued or new lands on the
        successor."""
        if not self._started:
            return -1
        if self._native_bridge is not None:
            socks = self._native_bridge.listener_sockets()
        elif self._listener is not None:
            socks = [self._listener]
        else:
            socks = []
        if not socks:
            return -1
        from . import hot_restart as _hot_restart
        return _hot_restart.handoff_listeners(path, socks, timeout_s)

    def drain(self, grace_ms: Optional[int] = None) -> int:
        """Enter lame-duck and finish in-flight work (≈ the graceful
        half of brpc ``Server::Stop`` + ``-graceful_quit_on_sigterm``):

        1. unpublish from the naming source (new clients resolve away);
        2. stop accepting (Python acceptor paused, engine listeners
           disarmed — listener FDS stay open for a hot-restart
           successor) and start stamping the lame-duck signal on every
           response, on all six lanes;
        3. reject NEW requests with ELAMEDUCK through the one shared
           admission stage (fail-fast retried on LB channels);
        4. wait — bounded by ``grace_ms`` / the ``drain_grace_ms`` flag
           — for in-flight requests, staged shm-ring slots and client-
           demux in-flight entries to settle;
        5. at grace expiry, force-close stragglers with the named
           reason ``drain_grace_expired``.

        Returns 0 when everything settled inside the grace, -1
        otherwise.  ``stop()`` afterwards is instant and client-
        invisible.  Idempotent while draining."""
        if not self._started:
            return -1
        if self._drain_state == DRAIN_DRAINING:
            return 0
        grace = int(grace_ms if grace_ms is not None
                    else get_flag("drain_grace_ms", 5000))
        deadline = _time.monotonic() + grace / 1e3
        self._drain_deadline_mono = deadline
        self._drain_state = DRAIN_DRAINING
        self.unpublish()
        # fleet visibility within ONE report interval: the drain +
        # lame-duck flight-recorder events, a final report that says
        # "draining", and an explicit registry deregister (bounded 1s
        # RPCs inside fleet — the grace budget is not spent here)
        from .. import fleet as _fleet
        _fleet.on_server_drain(self)
        if self._acceptor is not None:
            self._acceptor.pause_accept()
        if self._native_bridge is not None:
            # engine: disarm listeners + append the lame-duck TLV to
            # natively-built responses + decline new kind-4 matches
            # (new kind-5 stream opens decline under `stream_drain`)
            self._native_bridge.enter_lame_duck(
                bool(get_flag("enable_lame_duck", True)))
        # in-flight STREAMS settle too: each gets its current chunk
        # window flushed (bounded by the same grace) then a FIN
        # carrying the NAMED lame-duck reason — never cut mid-frame
        from ..streaming import drain_server_streams
        drain_server_streams(self, deadline)
        settled = self._wait_inflight_zero(deadline)
        if not settled:
            # in-flight stragglers: THOSE connections earn the named
            # force-close — data-plane residue below never does (its
            # gauges are process-global; a co-hosted client's steady
            # outbound traffic must not cost settled server conns
            # their sockets)
            self._force_close_stragglers()
        # data-plane residue inside the SAME deadline: a process must
        # not exit while a peer still maps one of its descriptors or a
        # demux table still expects a response.  NOTE both gauges are
        # process-wide (they cover this server's responses AND any
        # co-hosted client's calls): in a proxy process with unrelated
        # outbound load they may never read 0 — drain then reports -1
        # after the grace, with the server half itself fully settled.
        from ..kv import pages as _kv_pages
        from ..transport import client_lane as _client_lane
        from ..transport import shm_ring as _shm_ring
        shm_left = _shm_ring.drain_settle(deadline)
        lane_left = _client_lane.drain_settle(deadline)
        kv_left = _kv_pages.drain_settle(deadline)
        if shm_left or lane_left or kv_left:
            LOG.warning("drain grace expired with %d shm slot(s), "
                        "%d demux entrie(s) and %d kv page(s) "
                        "unsettled", shm_left, lane_left, kv_left)
        return 0 if settled and not shm_left and not lane_left \
            and not kv_left else -1

    def stop(self) -> int:
        """≈ Server::Stop: stop accepting, fail live connections.
        After a completed :meth:`drain` there is nothing live to fail —
        the restart is client-invisible."""
        if not self._started:
            return 0
        self._started = False
        self._drain_state = DRAIN_STOPPED
        self.unpublish()
        from .. import fleet as _fleet
        _fleet.on_server_stop(self)     # flight recorder + reporter reap
        if self._acceptor is not None:
            self._acceptor.stop_accept()
        if self._native_bridge is not None:
            self._native_bridge.stop()
            self._native_bridge = None
        if self._internal_acceptor is not None:
            self._internal_acceptor.stop_accept()
        self._listener = None
        self._stopped_event.set()
        with self._inflight_lock:
            # wake joiners even if in-flight never settles: their wait
            # is grace-bounded, not stop-gated
            self._drain_cv.notify_all()
        return 0

    def join(self, timeout: Optional[float] = None) -> None:
        """≈ Server::Join: blocks until stop() AND every in-flight
        request has settled (bounded by the drain grace — a handler
        that never returns cannot pin the process forever).  The old
        behavior returned the instant ``stop()`` fired, with handlers
        still running in a half-torn-down server."""
        self._stopped_event.wait(timeout)
        if not self._stopped_event.is_set():
            return                      # caller's timeout, not ours
        grace_s = int(get_flag("drain_grace_ms", 5000)) / 1e3
        deadline = _time.monotonic() + grace_s
        with self._inflight_lock:
            while self._inflight > 0:
                left = deadline - _time.monotonic()
                if left <= 0:
                    LOG.warning("join(): %d request(s) still in flight "
                                "at drain-grace expiry", self._inflight)
                    return
                self._drain_cv.wait(min(left, 0.05))

    def run_until_asked_to_quit(self) -> None:
        try:
            self.join()
        except KeyboardInterrupt:
            self.stop()
