"""Server — lifecycle + service registry.

Capability parity with /root/reference/src/brpc/server.cpp:746 (StartInternal),
:464 (AddBuiltinServices), server.h:59 (ServerOptions). Differences by
design: protocols already live in a process-global registry, so building
the acceptor's handler table is collecting every server-capable protocol;
worker sizing configures the fiber runtime.
"""

from __future__ import annotations

import socket as _socket
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from ..butil.endpoint import EndPoint, parse_endpoint
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..fiber import runtime as fiber_runtime
from ..protocol.base import list_protocols
from ..transport.acceptor import Acceptor
from ..transport.event_dispatcher import global_dispatcher
from ..transport.input_messenger import InputMessenger
from .method_status import MethodStatus
from .service import extract_methods, service_name_of


class ServerOptions:
    """≈ ServerOptions (server.h:59). Only capabilities the TPU build has
    wired so far; grows with the build."""

    __slots__ = ("num_workers", "max_concurrency", "method_max_concurrency",
                 "auth", "interceptor", "idle_timeout_s",
                 "internal_port", "server_info_name",
                 "native", "native_loops", "usercode_inline",
                 "ssl_cert", "ssl_key", "ssl_context",
                 "restful_mappings", "session_local_data_factory",
                 "tenant_fair_capacity", "tenant_weights")

    def __init__(self):
        self.num_workers = 0            # 0 = leave fiber runtime defaults
        # server-wide in-flight cap: an int (0 = off), OR a make_limiter
        # spec ("auto" / "timeout[:ms]" / "constant:N") / a
        # ConcurrencyLimiter instance — the whole server's admission
        # then adapts to measured latency (overload plane, ≈ brpc
        # -max_concurrency taking AdaptiveMaxConcurrency)
        self.max_concurrency: Any = 0
        # "Service.Method" -> int cap, "auto", "constant:N", or a
        # ConcurrencyLimiter instance; the "*" key is the default spec
        # applied to every method without its own entry
        self.method_max_concurrency: Dict[str, Any] = {}
        # overload plane, per-tenant fair admission: total concurrency
        # the tenant scheduler divides (0 = tenant layer accounts but
        # never rejects).  Weighted guaranteed shares come from
        # tenant_weights (default weight 1); capacity beyond the
        # guarantees is a shared free pool.
        self.tenant_fair_capacity = 0
        self.tenant_weights: Dict[str, float] = {}
        self.auth: Optional[Any] = None          # .verify(auth_data, cntl)
        self.interceptor: Optional[Callable] = None  # (cntl) -> (ok, code, text)
        self.idle_timeout_s = -1
        self.internal_port = -1
        self.server_info_name = ""
        # serve the main port through the native C++ IO engine (framed
        # protocols only; pair with internal_port for the HTTP portal).
        # Falls back to the Python transport if the engine can't build.
        self.native = False
        # 0 = placement-aware auto (one loop per core up to 4 — see
        # native_bridge.default_engine_loops); explicit values pin it
        self.native_loops = 0
        # run user code directly on the native engine's IO thread instead
        # of a fiber (≈ the reference's usercode_in_pthread,
        # /root/reference/src/brpc/details/usercode_backup_pool.h): saves a
        # thread handoff per request — the echo-class latency fast path.
        # Only enable when handlers never block (or begin_async() early).
        self.usercode_inline = False
        # TLS on the serving port (≈ ServerSSLOptions,
        # /root/reference/src/brpc/ssl_options.h:83): set cert+key paths,
        # or a ready ssl.SSLContext.  TLS serves through the Python
        # transport (the native engine speaks cleartext framed protocols).
        self.ssl_cert = ""
        self.ssl_key = ""
        self.ssl_context = None
        # restful routing (≈ restful.cpp): "PATH => Service.Method" pairs,
        # comma separated; a trailing /* captures the rest of the path
        # into cntl.http_unresolved_path.
        #   "/v1/echo => E.Echo, /files/* => Files.Get"
        self.restful_mappings = ""
        # SimpleDataPool factory (≈ simple_data_pool.h): per-request
        # reusable user data via cntl.session_local_data()
        self.session_local_data_factory = None


class _MethodEntry:
    __slots__ = ("fn", "request_type", "status", "service", "method_name",
                 "grpc_streaming", "raw_fn", "native_kind")

    def __init__(self, fn, request_type, status, service, method_name,
                 grpc_streaming=False, raw_fn=None, native_kind=None):
        self.fn = fn
        self.request_type = request_type
        self.grpc_streaming = grpc_streaming
        self.status = status
        self.service = service
        self.method_name = method_name
        self.raw_fn = raw_fn     # bytes-in/bytes-out latency-lane handler
        self.native_kind = native_kind   # C++ semantic ("echo"/"const")


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Any] = {}
        self._methods: Dict[Tuple[str, str], _MethodEntry] = {}
        self._listener: Optional[_socket.socket] = None
        self._acceptor: Optional[Acceptor] = None
        self._native_bridge = None
        self._internal_acceptor: Optional[Acceptor] = None
        self._internal_endpoint: Optional[EndPoint] = None
        self._messenger: Optional[InputMessenger] = None
        self._listen_endpoint: Optional[EndPoint] = None
        self._started = False
        self._stopped_event = threading.Event()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.version = ""
        self._restful = []           # parsed (segments, has_rest, entry_key)
        self._session_pool = None    # SimpleDataPool when factory set
        self._admission = None       # lazy AdmissionControl (overload plane)
        self._server_limiter = None  # adaptive server-wide cap (spec'd
        self._server_limiter_spec = None   # max_concurrency), parsed lazily

    # -- registry ----------------------------------------------------------

    def add_service(self, service: Any, name: str = "") -> int:
        """≈ Server::AddService. Method set is extracted by reflection;
        per-method request types come from the @method decorator."""
        if self._started:
            LOG.error("add_service after start")
            return -1
        sname = name or service_name_of(service)
        if sname in self._services:
            LOG.error("service %s already added", sname)
            return -1
        if sname == "redis" and hasattr(service, "on_command"):
            # RESP service: the shared port speaks redis to it
            # (≈ ServerOptions.redis_service, src/brpc/redis.h)
            self._services[sname] = service
            return 0
        if sname == "thrift" and hasattr(service, "handle"):
            # thrift framed-binary service on the shared port
            self._services[sname] = service
            return 0
        methods = extract_methods(service)
        if not methods:
            LOG.error("service %s has no public methods", sname)
            return -1
        self._services[sname] = service
        from ..policy.concurrency_limiter import (ConcurrencyLimiter,
                                                  make_limiter)
        default_mc = self.options.method_max_concurrency.get("*", 0)
        if isinstance(default_mc, ConcurrencyLimiter):
            # one INSTANCE as the default would be shared by reference
            # across every method — mixed latencies feeding one
            # adaptive state make the limit meaningless for all of
            # them.  Spec strings get a fresh limiter per method.
            LOG.error("method_max_concurrency['*'] must be a spec "
                      "(e.g. \"auto\"), not a limiter instance")
            del self._services[sname]
            return -1
        for mname, fn in methods.items():
            full = f"{sname}.{mname}"
            mc = self.options.method_max_concurrency.get(full, default_mc)
            limiter = None
            if isinstance(mc, ConcurrencyLimiter):
                limiter, mc = mc, 0
            elif isinstance(mc, str):
                limiter = make_limiter(mc)
                mc = 0
            status = MethodStatus(full, max_concurrency=mc, limiter=limiter)
            entry = _MethodEntry(
                fn=fn,
                request_type=getattr(fn, "_rpc_request_type", None),
                status=status,
                service=service,
                method_name=mname,
                grpc_streaming=getattr(fn, "_grpc_streaming", False),
                raw_fn=fn if getattr(fn, "_rpc_raw", False) else None,
                native_kind=getattr(fn, "_rpc_native", None),
            )
            self._methods[(sname, mname)] = entry
        return 0

    def find_method(self, service_name: str,
                    method_name: str) -> Optional[_MethodEntry]:
        return self._methods.get((service_name, method_name))

    def find_restful(self, parts) -> Optional[Tuple[_MethodEntry, str]]:
        """Match an HTTP path against restful_mappings
        (≈ /root/reference/src/brpc/restful.cpp pattern table).
        Returns (entry, unresolved_path) or None."""
        for segs, has_rest, key in self._restful:
            n = len(segs)
            if has_rest:
                if len(parts) < n or parts[:n] != segs:
                    continue
                entry = self._methods.get(key)
                if entry is not None:
                    return entry, "/".join(parts[n:])
            elif list(parts) == segs:
                entry = self._methods.get(key)
                if entry is not None:
                    return entry, ""
        return None

    def _parse_restful(self) -> None:
        self._restful = []
        spec = self.options.restful_mappings or ""
        for pair in spec.split(","):
            pair = pair.strip()
            if not pair:
                continue
            try:
                pattern, _, target = pair.partition("=>")
                pattern = pattern.strip()
                svc, _, mth = target.strip().rpartition(".")
                segs = [p for p in pattern.split("/") if p]
                has_rest = bool(segs) and segs[-1] == "*"
                if has_rest:
                    segs = segs[:-1]
                if (svc, mth) not in self._methods:
                    LOG.error("restful mapping %r: unknown method %s.%s",
                              pair, svc, mth)
                    continue
                self._restful.append((segs, has_rest, (svc, mth)))
            except ValueError:
                LOG.error("bad restful mapping %r", pair)
        # longest (most specific) patterns first; exact beats wildcard
        # at equal length
        self._restful.sort(key=lambda t: (-len(t[0]), t[1]))

    @property
    def services(self) -> Dict[str, Any]:
        return dict(self._services)

    @property
    def methods(self):
        return self._methods

    # -- server-wide concurrency + admission (overload plane) -------------

    @property
    def admission(self):
        """This server's AdmissionControl (lazy) — the ONE admission
        stage all five dispatch paths run (server/admission.py)."""
        ctl = self._admission
        if ctl is None:
            from .admission import AdmissionControl
            with self._inflight_lock:
                if self._admission is None:
                    self._admission = AdmissionControl(self)
                ctl = self._admission
        return ctl

    def server_limiter(self):
        """The adaptive server-wide concurrency limiter when
        ``options.max_concurrency`` is a spec/instance (None for the
        classic int cap).  Parsed lazily and re-parsed when the option
        object changes, so tests/operators may set it any time before
        traffic."""
        mc = self.options.max_concurrency
        if isinstance(mc, int):
            return None
        if mc is not self._server_limiter_spec:
            from ..policy.concurrency_limiter import (ConcurrencyLimiter,
                                                      make_limiter)
            self._server_limiter = mc if isinstance(mc, ConcurrencyLimiter) \
                else make_limiter(mc)
            self._server_limiter_spec = mc
        return self._server_limiter

    def on_request_in(self) -> bool:
        lim = self.server_limiter()
        if lim is not None:
            limit = lim.max_concurrency()
        else:
            limit = self.options.max_concurrency
        with self._inflight_lock:
            if limit > 0 and self._inflight >= limit:
                return False
            self._inflight += 1
            return True

    def on_request_out(self, tenant=None, error_code: int = 0,
                       latency_us: float = 0.0) -> None:
        """Settle one admitted request.  The five dispatch lanes pass
        the request's tenant (fair-admission slot release) and the
        measured outcome (the adaptive server-wide limiter's feed);
        legacy/error paths may still call it bare."""
        with self._inflight_lock:
            if self._inflight > 0:
                self._inflight -= 1
        if error_code or latency_us:
            lim = self._server_limiter
            if lim is not None:
                lim.on_responded(error_code, latency_us)
        if tenant is not None and self._admission is not None:
            self._admission.release(tenant)

    @property
    def inflight(self) -> int:
        return self._inflight

    def _server_ssl_context(self):
        opts = self.options
        if opts.ssl_context is not None:
            return opts.ssl_context
        if not opts.ssl_cert:
            return None
        import ssl as _ssl
        ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(opts.ssl_cert, opts.ssl_key or None)
        return ctx

    # -- lifecycle ---------------------------------------------------------

    def start(self, addr: Any = "127.0.0.1:0") -> int:
        """≈ Server::Start. ``addr`` is "ip:port" (port 0 = ephemeral),
        an EndPoint, or a bare port int."""
        if self._started:
            return -1
        if isinstance(addr, int):
            ep = EndPoint(host="0.0.0.0", port=addr)
        elif isinstance(addr, EndPoint):
            ep = addr
        else:
            ep = parse_endpoint(str(addr))
        if self.options.num_workers > 0:
            fiber_runtime.set_concurrency(self.options.num_workers)

        lst = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        lst.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        if self.options.native and hasattr(_socket, "SO_REUSEPORT"):
            # the native bridge shards accept across its loops with one
            # SO_REUSEPORT listener per loop; the PRIMARY socket must
            # carry the option from before bind or the kernel refuses
            # the shard binds (mixed-mode).  Gated on the flag AND a
            # multi-loop resolution: REUSEPORT also waives EADDRINUSE
            # against other same-UID processes, so a server that will
            # never shard must not pay that safety loss.
            from ..butil.flags import get_flag as _get_flag
            from ..transport.native_bridge import default_engine_loops
            nloops = self.options.native_loops or default_engine_loops()
            if nloops > 1 and bool(_get_flag("engine_reuseport", True)):
                try:
                    lst.setsockopt(_socket.SOL_SOCKET,
                                   _socket.SO_REUSEPORT, 1)
                except OSError:
                    pass
        try:
            lst.bind(ep.to_sockaddr())
        except OSError as e:
            LOG.error("bind %s: %s", ep, e)
            lst.close()
            return -1
        lst.listen(1024)
        host, port = lst.getsockname()[:2]
        self._listen_endpoint = EndPoint(host=host, port=port)
        self._listener = lst

        if self.options.restful_mappings:
            self._parse_restful()
        if self.options.session_local_data_factory is not None:
            from ..butil.simple_data_pool import SimpleDataPool
            self._session_pool = SimpleDataPool(
                self.options.session_local_data_factory)
        # handler table = every registered server-capable protocol
        # (≈ Server::BuildAcceptor collecting protocols, server.cpp:572);
        # importing the modules registers the builtins
        from ..ici import endpoint as _ici        # noqa: F401
        from ..protocol import h2_rpc as _h2      # noqa: F401
        from ..protocol import http as _http      # noqa: F401
        from ..protocol import resp as _resp      # noqa: F401
        from ..protocol import streaming as _str  # noqa: F401
        from ..protocol import thrift_proto as _t  # noqa: F401
        from ..protocol import tpu_std as _tpu    # noqa: F401
        handlers = [p for p in list_protocols() if p.support_server]
        self._messenger = InputMessenger(handlers, arg=self)
        ssl_ctx = self._server_ssl_context()
        if self.options.native and ssl_ctx is None:
            from ..native import load as load_native
            native_mod = load_native()
            if native_mod is not None:
                from ..transport.native_bridge import NativeBridge
                self._native_bridge = NativeBridge(
                    self, native_mod, loops=self.options.native_loops)
                self._native_bridge.listen(lst)
            else:
                LOG.warning("native engine unavailable; serving %s through "
                            "the Python transport", ep)
        elif self.options.native and ssl_ctx is not None:
            LOG.warning("TLS serving uses the Python transport; "
                        "native engine disabled for %s", ep)
        if self._native_bridge is None:
            self._acceptor = Acceptor(self._messenger, ssl_context=ssl_ctx)
            self._acceptor.start_accept(lst)

        # Optional second, operator-only port: builtin portal pages (flag
        # mutation, rpcz, profilers …) are served ONLY to connections
        # accepted here when set (≈ server.cpp:1079-1086).
        if self.options.internal_port >= 0:
            ilst = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            ilst.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            try:
                ilst.bind((host, self.options.internal_port))
            except OSError as e:
                LOG.error("bind internal port %d: %s",
                          self.options.internal_port, e)
                ilst.close()
                self._acceptor.stop_accept()
                self._acceptor = None
                self._messenger = None
                self._listener = None
                self._listen_endpoint = None
                return -1
            ilst.listen(128)
            self._internal_endpoint = EndPoint(host=host,
                                               port=ilst.getsockname()[1])
            self._internal_acceptor = Acceptor(self._messenger,
                                               tag="internal")
            self._internal_acceptor.start_accept(ilst)
        self._started = True
        self._stopped_event.clear()
        from ..bvar.dump import ensure_dumper
        ensure_dumper()     # no-op unless the bvar_dump flag is on
        LOG.info("Server started at %s (%d services, %d methods)",
                 self._listen_endpoint, len(self._services),
                 len(self._methods))
        return 0

    @property
    def listen_endpoint(self) -> Optional[EndPoint]:
        return self._listen_endpoint

    @property
    def internal_endpoint(self) -> Optional[EndPoint]:
        return self._internal_endpoint

    @property
    def running(self) -> bool:
        return self._started

    def connection_count(self) -> int:
        n = self._acceptor.connection_count() if self._acceptor else 0
        if self._native_bridge is not None:
            n += self._native_bridge.connection_count()
        if self._internal_acceptor is not None:
            n += self._internal_acceptor.connection_count()
        return n

    def stop(self) -> int:
        """≈ Server::Stop: stop accepting, fail live connections."""
        if not self._started:
            return 0
        self._started = False
        if self._acceptor is not None:
            self._acceptor.stop_accept()
        if self._native_bridge is not None:
            self._native_bridge.stop()
            self._native_bridge = None
        if self._internal_acceptor is not None:
            self._internal_acceptor.stop_accept()
        self._listener = None
        self._stopped_event.set()
        return 0

    def join(self, timeout: Optional[float] = None) -> None:
        """≈ Server::Join (blocks until stop())."""
        self._stopped_event.wait(timeout)

    def run_until_asked_to_quit(self) -> None:
        try:
            self.join()
        except KeyboardInterrupt:
            self.stop()
