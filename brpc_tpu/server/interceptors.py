"""One pipeline, N lane bindings — the per-lane-compiled interceptor
chain (ROADMAP item 1's extraction, first bound by the kind-5 streaming
lane).

Every server lane runs the same cross-cutting stages around user code:

    admission      the SHARED overload-plane stage (server/admission) —
                   server cap, adaptive method cap, CoDel, tenant fair
                   admission, drain rejection
    deadline shed  queue-expired requests answered ERPCTIMEDOUT before
                   user code runs, anchored at the engine parse stamp
    trace extract  rpcz span sampling / forced spans for traced
                   requests, backdated to the parse stamp
    MethodStatus   per-method accounting + rpcz span completion
    telemetry      latency fed to the adaptive limiters through
                   on_responded / on_request_out

Until this module, those stages were hand-replicated across six lane
bodies (round 12's shared admission stage was the first slice; round
14's lane linter pins the invariants mechanically).  :func:`compile_chain`
composes them ONCE per (server, method, lane) into a flat
``(enter, settle)`` closure pair — all per-entry state bound into
default args, zero per-call abstraction cost (≈ brpc's per-protocol
``process_request`` policy callbacks, protocol.h:92-146).  A lane that
binds the chain cannot drop a stage or reorder admission after user
code: the stages live HERE, the lane body only calls ``enter`` before
user code and ``settle`` (or ``cntl.finish`` escalation) after.

:func:`compile_chain` is tpu_std-flavored (rejections serialize through
the classic ``_send_error`` builder, byte-identical with the hand-rolled
lanes); :func:`compile_http_chain` is the HTTP binding of the same
stages — rejections serialize through the shared ``http_reject``
helper, traces arrive as W3C ``traceparent`` headers, deadlines as
``x-deadline-ms``.  The lane linter (tools/check/lanes.py) analyzes
each chain's ``enter`` body for the admission-before-shed ordering and
the lane body for the enter-before-user-code ordering — the binding is
machine-checked, not a convention.
"""

from __future__ import annotations

from time import monotonic_ns as _mono_ns

from ..butil.iobuf import IOBuf
from ..butil.status import Errno
from ..deadline import arm as _arm_deadline
from ..deadline import maybe_shed as _maybe_shed
from ..protocol.meta import RpcMeta
from ..rpcz import backdate_span, start_server_span
from .admission import admit as _admit
from .controller import ServerController
from .rpc_dispatch import _send_error, _send_response

_ELOGOFF = int(Errno.ELOGOFF)


def compile_chain(server, entry, lane: str):
    """Compile the cross-cutting stages for one (server, method, lane)
    into a flat ``(enter, settle)`` pair.

    ``enter(sock, cid, payload_len, att, dom, nonce, recv_ns, trace,
    tmo, tenant)`` runs admission → deadline shed → trace extract and
    returns a ready :class:`ServerController`, or ``None`` when the
    request was rejected/shed (the client is already answered and every
    taken count undone — the lane must not touch the request again).

    ``settle(cntl, response_len)`` is the fast-completion epilogue:
    MethodStatus + limiter latency feed + tenant slot release + span
    finish.  Escalations (``cntl.finish``) settle through the classic
    completion instead and must NOT also call ``settle``.
    """
    status = entry.status
    full_name = status.full_name
    svc, _, mth = full_name.partition(".")

    def _send(cntl, response, _server=server, _entry=entry):
        _send_response(_server, _entry, cntl, response)

    def enter(sock, cid, payload_len, att, dom, nonce, recv_ns, trace,
              tmo, tenant,
              _server=server, _entry=entry, _status=status, _svc=svc,
              _mth=mth, _send=_send, _admit_stage=_admit,
              _shed=_maybe_shed, _arm=_arm_deadline,
              _sample=start_server_span, _backdate=backdate_span,
              _lane=lane):
        if not _server.running:
            _send_error(sock, cid, _ELOGOFF, "server is stopping")
            return None
        # ---- admission: the ONE shared overload-plane stage, FIRST —
        # CoDel sojourn and the adaptive limiters measure from the
        # engine's CLOCK_MONOTONIC parse stamp, so native batch
        # queueing counts against the limit
        rej = _admit_stage(_server, _entry, _lane, tenant,
                           recv_ns // 1000)
        if rej is not None:
            # rejection serialization through the SHARED classic error
            # builder (drain rejections carry the lame-duck TLV)
            _send_error(sock, cid, rej.code, rej.text, server=_server)
            return None
        meta = RpcMeta()
        meta.correlation_id = cid
        meta.service_name = _svc
        meta.method_name = _mth
        if dom is not None:
            sock.ici_peer_domain = dom
            meta.ici_domain = dom
        if nonce is not None and sock.ici_conn_token is None:
            sock.ici_conn_token = nonce     # first write wins
        if trace is not None:
            meta.trace_id, meta.span_id, meta.parent_span_id = trace
        if tenant is not None:
            meta.tenant = tenant            # slot-release key
        na = len(att) if att is not None else 0
        if na:
            meta.attachment_size = na
        cntl = ServerController(meta, sock.remote_side, sock.id, _send)
        cntl.server = _server
        # latency measured from the ENGINE's frame-parse stamp (native
        # queueing is where an overloaded server's latency lives)
        cntl.begin_time_us = recv_ns // 1000
        if tmo is not None:
            meta.timeout_ms = tmo
            _arm(cntl, tmo, recv_ns // 1000)
        if na:
            ab = IOBuf()
            ab.append_user_data(att)
            cntl._req_att = ab
        # ---- trace extract: sampled spans + FORCED spans for traced
        # requests, backdated so they cover native queueing
        span = _sample(_status.full_name, meta, sock.remote_side)
        if span is not None:
            span.request_size = payload_len + na
            _backdate(span, recv_ns)
            cntl.span = span
        # ---- deadline shed, AFTER admission (rejections are cheaper
        # than armed deadlines), BEFORE user code
        if tmo is not None and _shed(cntl, _lane, _status.full_name):
            cntl.finish(None)
            return None
        return cntl

    def settle(cntl, response_len,
               _status=status, _server=server, _ns=_mono_ns):
        """Fast-completion epilogue: MethodStatus settle (feeds the
        adaptive limiters), tenant slot release, span completion."""
        latency_us = _ns() // 1000 - cntl.begin_time_us
        _status.on_responded(0, latency_us)
        _server.on_request_out(tenant=cntl.request_meta.tenant,
                               latency_us=latency_us)
        if cntl._session_data is not None \
                and _server._session_pool is not None:
            _server._session_pool.give_back(cntl._session_data)
            cntl._session_data = None
        span = cntl.span
        if span is not None:
            span.response_size = response_len
            span.finish(0)

    return enter, settle


def compile_rpc_chain(server, entry):
    """The FULL tpu_std lane's binding of the interceptor chain —
    ROADMAP item 1's FIFTH (and final) port: the classic fiber-task
    dispatch path (``rpc_dispatch.process_rpc_request``) now binds the
    same compiled stages the slim/HTTP/streaming lanes do, instead of
    hand-replicating them.

    ``enter(msg, sock, send)`` runs the cross-cutting prologue on a
    parsed :class:`RpcMessage`: running check → admission → controller
    construction (with the lane's ``send`` funnel as its completion
    callback) → attachment split → ici domain/conn/descriptor staging
    → shm negotiation → trace extract → deadline arm + shed.  Returns
    a ready :class:`ServerController`, or ``None`` when the request
    was rejected/shed — the client is already answered through the
    classic ``_send_error`` builder and every taken count undone.

    ``settle(cntl, response)`` is the accounting epilogue every
    completion funnels through (the lane's ``send`` closure calls it
    right before the wire serializer): MethodStatus settle + limiter
    latency feed — including the trivial-shape slim escalation's
    recorder-only variant, symmetric with the slim template's own
    completion."""
    status = entry.status
    _EREQUEST = int(Errno.EREQUEST)

    def enter(msg, sock, send,
              _server=server, _entry=entry, _status=status,
              _admit_stage=_admit, _shed=_maybe_shed,
              _arm=_arm_deadline, _sample=start_server_span):
        meta = msg.meta
        cid = meta.correlation_id
        if not _server.running:
            _send_error(sock, cid, _ELOGOFF, "server is stopping",
                        request_meta=meta)
            return None
        # ---- admission: the ONE shared overload-plane stage, FIRST —
        # server cap, adaptive method cap, CoDel queue discipline,
        # per-tenant fair admission; a rejected request is answered
        # ELIMIT before auth/parse/handler burn any time on it
        rej = _admit_stage(_server, _entry, "tpu_std", meta.tenant,
                           getattr(msg, "recv_us", 0) or None)
        if rej is not None:
            # rejection serialization through the SHARED classic error
            # builder (drain rejections carry the lame-duck TLV)
            _send_error(sock, cid, rej.code, rej.text,
                        request_meta=meta, server=_server)
            return None
        cntl = ServerController(meta, sock.remote_side, sock.id, send)
        cntl.server = _server
        try:
            cntl.request_attachment = msg.split_attachment()
        except ValueError as e:
            _status.on_responded(_EREQUEST, 0)
            _server.on_request_out(tenant=meta.tenant)
            _send_error(sock, cid, _EREQUEST, str(e), request_meta=meta)
            return None
        if meta.ici_domain:
            # learn the peer's device-fabric domain (enables device-
            # resident response attachments from the very first exchange)
            sock.ici_peer_domain = meta.ici_domain
        if meta.ici_conn and sock.ici_conn_token is None:
            # pin the initiator's connection nonce (first write wins):
            # the conn identity descriptor binding uses on both ends
            sock.ici_conn_token = meta.ici_conn
        if meta.ici_desc:
            from ..ici.endpoint import split_device_attachment
            cntl.request_attachment, cntl.request_device_attachment = \
                split_device_attachment(meta, cntl.request_attachment,
                                        sock.id)
        if meta.shm_offer or meta.shm_accept or meta.shm_release \
                or meta.shm_desc:
            # shm data plane: process ring negotiation/credit TLVs and
            # resolve a request descriptor into a zero-copy view of the
            # client's ring (the attachment never rode the frame)
            from ..transport import shm_ring
            view, handle, accept = \
                shm_ring.server_on_request_meta(sock, meta)
            cntl._shm_extra = accept
            cntl._shm_handle = handle
            if view is not None:
                ab = IOBuf()
                # file_ref lets this block spill via os.sendfile if user
                # code forwards it onto a TCP byte lane (proxy shapes)
                ab.append_user_data(view, file_ref=handle.file_ref)
                cntl.request_attachment = ab
            elif meta.shm_desc:
                # the client believes the attachment lives at this
                # descriptor; failing loudly beats handing user code an
                # empty attachment
                _status.on_responded(_EREQUEST, 0)
                _server.on_request_out(tenant=meta.tenant)
                _send_error(sock, cid, _EREQUEST,
                            "unresolvable shm attachment descriptor",
                            request_meta=meta)
                return None
        # ---- trace extract: sampled spans + forced spans for traced
        # requests
        span = _sample(_status.full_name, meta, sock.remote_side)
        if span is not None:
            span.request_size = len(msg.payload) \
                + len(cntl.request_attachment)
            cntl.span = span
        # ---- deadline plane, AFTER admission (rejections are cheaper
        # than armed deadlines), BEFORE user code: anchor TLV 13's
        # remaining budget at the message's PARSE time (fiber-pool
        # queueing between cut and dispatch counts against it), then
        # shed doomed work.  An explicit on-wire 0 (clients stamp ≥ 1)
        # means expired-at-arrival.
        if meta.timeout_ms or getattr(meta, "timeout_present", False):
            _arm(cntl, meta.timeout_ms,
                 getattr(msg, "recv_us", 0) or None)
            if _shed(cntl, "tpu_std", _status.full_name):
                cntl.finish(None)
                return None
        return cntl

    def settle(cntl, response,
               _status=status, _server=server, _ns=_mono_ns):
        """Accounting epilogue (every completion shape — sync return,
        async finish, error escalation — funnels through here exactly
        once, inside the lane's send closure): MethodStatus settle +
        limiter latency feed."""
        latency_us = _ns() // 1000 - cntl.begin_time_us
        if cntl._slim_fast:
            # trivial-shape slim fast item escalated to the classic
            # completion: no admission layer is configured and its
            # in-flight counts were never taken (net-zero within the
            # burst; admitted verdicts flush per burst) — feed the
            # per-method recorders only
            cntl._slim_fast = False
            if cntl.error_code == 0:
                _status.latency << latency_us
            else:
                _status.errors << 1
            return
        _status.on_responded(cntl.error_code, latency_us)
        _server.on_request_out(tenant=cntl.request_meta.tenant,
                               error_code=cntl.error_code,
                               latency_us=latency_us)

    return enter, settle


def compile_http_chain(server, entry):
    """The HTTP binding of the interceptor chain (ROADMAP item 1's
    third port): same stages, HTTP spellings — tenant from
    ``x-tenant``, trace from W3C ``traceparent``, deadline from
    ``x-deadline-ms``, rejections through the shared ``http_reject``
    helper with the drain plane's lame-duck headers.

    ``enter(msg, sock, svc, mth, unresolved, send)`` runs admission →
    trace extract → deadline arm/shed and returns a ready
    :class:`ServerController` (with ``send`` as its completion
    callback), or ``None`` when the request was rejected/shed — the
    client is already answered.

    ``settle(cntl, response_len)`` is the completion epilogue every
    response path funnels through: MethodStatus + limiter latency feed
    + tenant slot release + span completion.  The lane's ``send``
    closure calls it exactly once per request, right before the bytes
    go out (or in place of them when the socket is gone)."""
    from ..butil.time_utils import monotonic_us
    from ..deadline import parse_deadline_ms as _parse_deadline_ms
    from ..protocol.http import build_response
    from ..rpcz import parse_traceparent
    from .admission import http_reject
    # lazy: http_dispatch imports this module to bind the chain
    from .http_dispatch import drain_response_args

    status = entry.status

    def enter(msg, sock, svc, mth, unresolved, send,
              _server=server, _entry=entry, _status=status,
              _admit_stage=_admit, _shed=_maybe_shed,
              _arm=_arm_deadline, _sample=start_server_span,
              _parse_tp=parse_traceparent,
              _parse_dl=_parse_deadline_ms, _reject=http_reject,
              _drain_args=drain_response_args, _build=build_response):
        # ---- admission: the ONE shared overload-plane stage, FIRST
        # (CoDel sojourn measured from the message's parse stamp)
        tenant = msg.headers.get("x-tenant")
        rej = _admit_stage(_server, _entry, "http", tenant,
                           getattr(msg, "recv_us", 0) or None)
        if rej is not None:
            # rejection serialization through the SHARED HTTP helper
            # (503 + Retry-After + reason; lame-duck headers in drain)
            status_code, body, extra = _reject(rej)
            extra, ka = _drain_args(_server, extra, msg.keep_alive)
            sock.write(_build(status_code, body, headers=extra,
                              keep_alive=ka))
            return None
        meta = RpcMeta()
        meta.service_name = svc
        meta.method_name = mth
        if tenant:
            meta.tenant = tenant.encode("utf-8", "replace")
        # ---- trace extract: W3C trace context → the internal trace
        # model (the server span parents to the caller's span id,
        # exactly like the tpu_std meta's trace/span TLVs)
        tp_header = msg.headers.get("traceparent")
        if tp_header:
            tp = _parse_tp(tp_header)
            if tp is not None:
                meta.trace_id, meta.span_id = tp
        # x-deadline-ms: the HTTP/1.1 spelling of tpu_std's remaining-
        # deadline TLV 13 (0 = already expired); kept in a local too —
        # meta.timeout_ms == 0 conventionally means "none"
        dl_ms = _parse_dl(msg.headers.get("x-deadline-ms"))
        if dl_ms is not None:
            meta.timeout_ms = dl_ms
        cntl = ServerController(meta, sock.remote_side, sock.id, send)
        cntl.server = _server
        cntl.http_method = msg.method
        cntl.http_path = msg.path
        cntl.http_unresolved_path = unresolved
        cntl.span = _sample(_status.full_name, meta, sock.remote_side)
        if cntl.span is not None:
            cntl.span.request_size = len(msg.body)
        if dl_ms is not None:
            # deadline plane: anchor the propagated budget at the
            # message's PARSE time (queueing between protocol cut and
            # the bridge counts against it), then shed doomed work
            # before body parsing or the handler burn any time on it
            _arm(cntl, dl_ms, getattr(msg, "recv_us", 0) or None)
            if _shed(cntl, "http", _status.full_name):
                cntl.finish(None)
                return None
        return cntl

    def settle(cntl, response_len,
               _status=status, _server=server, _us=monotonic_us):
        """Completion epilogue (every response shape — success, error,
        progressive headers, socket-gone — funnels through here once):
        MethodStatus settle, limiter latency feed, span completion."""
        latency_us = _us() - cntl.begin_time_us
        _status.on_responded(cntl.error_code, latency_us)
        _server.on_request_out(tenant=cntl.request_meta.tenant,
                               error_code=cntl.error_code,
                               latency_us=latency_us)
        span = cntl.span
        if span is not None:
            span.response_size = response_len
            span.finish(cntl.error_code)

    return enter, settle


def compile_http_slim_chain(server, entry, svc: str, mth: str,
                            http_method: str):
    """The kind-4 (slim native HTTP) binding of the interceptor chain
    — ROADMAP item 1's FOURTH port: same stages as
    :func:`compile_http_chain`, slim-lane spellings.  The engine hands
    the shim raw header VALUES (``traceparent`` / ``x-deadline-ms`` /
    ``x-tenant``) instead of a parsed message, timestamps are the
    engine's CLOCK_MONOTONIC parse stamp (spans backdated over native
    queueing), and a rejection serializes as the lane's
    ``(status, header_block, body)`` tuple riding the burst's single
    coalesced writev — byte-identical with ``build_response``'s
    output.

    ``enter(body_len, conn_id, remote_side, recv_ns, send,
    traceparent, deadline, tenant)`` returns ``(cntl, early)``:
    ``(cntl, None)`` when the request may proceed, ``(None, tuple)``
    for an admission rejection (the tuple is the engine's inline
    response), ``(None, None)`` when the deadline shed already
    completed through ``send`` (the lane returns its parked cell).

    ``settle(cntl, response_len)`` is the completion epilogue the
    lane's ``send`` closure funnels every response shape through."""
    from ..butil.time_utils import monotonic_us
    from ..deadline import parse_deadline_ms as _parse_deadline_ms
    from ..rpcz import parse_traceparent
    from .admission import http_reject
    # lazy: http_slim imports this module to bind the chain
    from .http_slim import _hdr_block

    status = entry.status
    full_name = status.full_name
    path = f"/{svc}/{mth}"

    def enter(body_len, conn_id, remote_side, recv_ns, send,
              traceparent, deadline, tenant,
              _server=server, _entry=entry, _status=status, _svc=svc,
              _mth=mth, _http_method=http_method, _path=path,
              _full=full_name, _admit_stage=_admit,
              _shed=_maybe_shed, _arm=_arm_deadline,
              _sample=start_server_span, _backdate=backdate_span,
              _parse_tp=parse_traceparent,
              _parse_dl=_parse_deadline_ms, _reject=http_reject,
              _hdr=_hdr_block):
        # ---- admission: the ONE shared overload-plane stage, FIRST —
        # CoDel sojourn and the limiters measure from the ENGINE's
        # parse stamp, so native batch queueing counts
        rej = _admit_stage(_server, _entry, "http_slim", tenant,
                           recv_ns // 1000)
        if rej is not None:
            # rejection serialization through the SHARED HTTP helper,
            # as a slim tuple the engine coalesces into the burst's
            # writev (503 + Retry-After; lame-duck headers in drain)
            st, rbody, extra = _reject(rej)
            return None, (st, _hdr("text/plain", extra), rbody)
        meta = RpcMeta()
        meta.service_name = _svc
        meta.method_name = _mth
        if tenant is not None:
            meta.tenant = tenant        # fair-admission slot release
        # ---- trace extract: raw W3C header value → the internal
        # trace model (explicitly traced requests STAY on the slim
        # lane, span parented to the caller)
        if traceparent is not None:
            tp = _parse_tp(traceparent)
            if tp is not None:
                meta.trace_id, meta.span_id = tp
        # x-deadline-ms: remaining budget, 0 = already expired (meta
        # keeps it for observability; the armed cntl deadline is what
        # enforcement reads)
        dl_ms = _parse_dl(deadline)
        if dl_ms is not None:
            meta.timeout_ms = dl_ms
        cntl = ServerController(meta, remote_side, conn_id, send)
        cntl.server = _server
        # latency anchored at the ENGINE's parse stamp, not shim
        # entry: limiter/MethodStatus samples include native queueing
        cntl.begin_time_us = recv_ns // 1000
        cntl.http_method = _http_method
        cntl.http_path = _path
        cntl.http_unresolved_path = ""
        if dl_ms is not None:
            _arm(cntl, dl_ms, recv_ns // 1000)
        span = _sample(_full, meta, remote_side)
        if span is not None:
            span.request_size = body_len
            _backdate(span, recv_ns)
            cntl.span = span
        # ---- deadline shed, AFTER admission, BEFORE user code: the
        # finish below completes through the lane's send closure,
        # which parks the 500 + x-rpc-error-code tuple in its cell
        if dl_ms is not None and _shed(cntl, "http_slim", _full):
            cntl.finish(None)
            return None, None
        return cntl, None

    def settle(cntl, response_len,
               _status=status, _server=server, _us=monotonic_us):
        """Completion epilogue (every response shape — success, error,
        progressive headers — funnels through here exactly once):
        MethodStatus settle, limiter latency feed, span completion."""
        latency_us = _us() - cntl.begin_time_us
        _status.on_responded(cntl.error_code, latency_us)
        _server.on_request_out(tenant=cntl.request_meta.tenant,
                               error_code=cntl.error_code,
                               latency_us=latency_us)
        span = cntl.span
        if span is not None:
            span.response_size = response_len
            span.finish(cntl.error_code)

    return enter, settle
