"""Server-side request processing for tpu_std frames.

≈ ProcessRpcRequest + SendRpcResponse
(/root/reference/src/brpc/policy/baidu_rpc_protocol.cpp:314,139): find the
method, run admission (interceptor, auth, concurrency), decompress+parse,
call user code on the current fiber task, send exactly one response.
"""

from __future__ import annotations

from typing import Any

from ..butil.flags import get_flag
from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..deadline import inherit_deadline
from ..protocol import compress as compress_mod
from ..protocol.meta import RpcMeta
from ..protocol.tpu_std import RpcMessage, pack_frame, parse_payload, serialize_payload
from ..tools import rpc_dump as _rpc_dump
from ..transport.socket import Socket
from .controller import ServerController


def _send_error(sock: Socket, correlation_id: int, code: int,
                text: str, request_meta: RpcMeta = None,
                server=None) -> None:
    if request_meta is not None and request_meta.ici_desc:
        # rejected before the device attachment was split: return the
        # client's posted window credit
        from ..ici.endpoint import ack_unused
        ack_unused(request_meta, sock.id)
    meta = RpcMeta()
    meta.correlation_id = correlation_id
    meta.error_code = int(code)
    meta.error_text = text
    if server is not None and server.lame_duck_signal_on:
        # drain: every error frame (incl. the ELAMEDUCK rejection
        # itself) tells the peer to re-resolve
        meta.lame_duck = 1
    sock.write(pack_frame(meta, IOBuf()))


import struct as _struct

from ..protocol.meta import (LAME_DUCK_TLV, TAG_ICI_DOMAIN,
                             TLV_ATTACHMENT, TLV_CORRELATION, encode_tlv)

_CID_TAG = TLV_CORRELATION
_ATT_TAG = TLV_ATTACHMENT
_domain_tlv_cache = None


def _domain_tlv() -> bytes:
    """Pre-encoded T_ICI_DOMAIN TLV for this process (empty when ici is
    off).  The domain id is fixed per process, so encode it once."""
    global _domain_tlv_cache
    if _domain_tlv_cache is None:
        from ..ici.endpoint import ici_enabled, local_domain_id
        if ici_enabled():
            _domain_tlv_cache = encode_tlv(TAG_ICI_DOMAIN, local_domain_id())
        else:
            _domain_tlv_cache = b""
    return _domain_tlv_cache


def _chain_for(server, entry):
    """The entry's compiled tpu_std interceptor chain, built once per
    (server, method) and cached on the entry (the import is lazy:
    interceptors binds this module's error/wire builders at its top)."""
    chain = entry.chain
    if chain is None:
        from .interceptors import compile_rpc_chain
        chain = entry.chain = compile_rpc_chain(server, entry)
    return chain


def _send_response(server, entry, cntl: ServerController,
                   response: Any) -> None:
    """Classic completion: the chain's accounting settle (MethodStatus
    + limiter feed — including the slim escalation's recorder-only
    variant) then the wire serializer.  Slim-lane escalations land
    here directly; the full lane funnels through its own send closure,
    which spells the same two halves."""
    _chain_for(server, entry)[1](cntl, response)
    _respond_wire(server, entry, cntl, response)


def _respond_wire(server, entry, cntl: ServerController,
                  response: Any) -> None:
    sock = Socket.address(cntl.socket_id)
    if cntl.request_device_attachment is not None:
        # invariant the client's sync fast lane relies on: the credit-
        # return for a request descriptor always PRECEDES the response
        # on the wire.  Redeemed in-handler ⇒ the ack is already queued;
        # never redeemed (handler ignored it / failed early) ⇒ settle
        # acks it now.  Handlers must redeem before finishing the RPC.
        cntl.request_device_attachment.settle()
    # shm data plane, response side: negotiation TLVs the response MUST
    # carry (capability accept + our ring spec), and — when the peer can
    # resolve our descriptors — the response attachment re-described
    # into shared memory instead of riding the frame (echo-class
    # responses re-describe the REQUEST's slot: zero data motion).
    # Descriptor staging is DEFERRED until the response is guaranteed
    # to leave (after serialization succeeds): staging first would leak
    # the tx-ring slot when a later step downgrades to an error frame.
    shm_extra = cntl._shm_extra
    shm_desc = b""

    def _shm_describe():
        nonlocal shm_desc
        if (cntl.failed or cntl._resp_att is None
                or not len(cntl._resp_att)):
            return
        from ..transport import shm_ring
        if (getattr(sock, "shm", None) is not None
                and not cntl.response_compress_type
                and cntl.response_device_attachment is None):
            shm_desc, _wire_att = shm_ring.describe_response_att(
                sock, cntl._resp_att, cntl._shm_handle)
            if shm_desc:
                cntl._resp_att = _wire_att  # None: attachment rides shm
        elif (shm_ring.lane_enabled() and len(cntl._resp_att)
                >= int(get_flag("rpc_shm_threshold"))):
            # an otherwise-eligible attachment kept off the lane by the
            # response's shape — name the reason (error responses are
            # not data-plane traffic and stay uncounted)
            if cntl.response_compress_type:
                shm_ring.count_fallback("shm_compressed")
            elif cntl.response_device_attachment is not None:
                shm_ring.count_fallback("shm_device_combo")
            else:                       # peer never spoke a shm TLV
                shm_ring.count_fallback("shm_peer_no_cap")

    if cntl.span is not None:
        cntl.span.finish(cntl.error_code)
    elif (not cntl.failed and sock is not None
            and not cntl._accepted_stream_id
            and not cntl.response_compress_type
            and cntl.response_device_attachment is None
            and isinstance(response, (bytes, bytearray, memoryview))):
        # echo-class fast path: flat TLV meta, no IOBuf/RpcMeta churn.
        # The response is bytes already — nothing can fail past here,
        # so staging is safe now
        _shm_describe()
        att = cntl._resp_att
        na = len(att) if att is not None else 0
        mb = _CID_TAG + _struct.pack("<Q", cntl.request_meta.correlation_id)
        if na:
            mb += _ATT_TAG + _struct.pack("<I", na)
        if cntl.request_meta.ici_domain:
            # answer the device-fabric domain exchange (cached TLV)
            mb += _domain_tlv()
        if shm_extra or shm_desc:
            mb += shm_extra + shm_desc
        if server.lame_duck_signal_on:
            # drain: in-flight work still completes, and its response
            # carries the re-resolve signal (pre-encoded TLV 23)
            mb += LAME_DUCK_TLV
        head = (b"TRPC"
                + _struct.pack("<II", len(mb) + len(response) + na, len(mb))
                + mb)
        if na:
            sock.write_parts((head, response) + tuple(att.backing_views()))
        else:
            sock.write_parts((head, response))
        return
    if cntl._accepted_stream_id and (cntl.failed or sock is None):
        # the client will never bind: close the orphaned accepted stream
        from ..streaming import find_stream
        s = find_stream(cntl._accepted_stream_id)
        if s is not None:
            s._close_local(notify_peer=False)
        cntl._accepted_stream_id = 0
    if sock is None:
        return      # connection died; response dropped like the reference
    meta = RpcMeta()
    meta.correlation_id = cntl.request_meta.correlation_id
    if server.lame_duck_signal_on:
        meta.lame_duck = 1          # drain: peers re-resolve away
    if cntl.request_meta.ici_domain:
        # answer the domain exchange so the client can go device-resident
        from ..ici.endpoint import ici_enabled, local_domain_id
        if ici_enabled():
            meta.ici_domain = local_domain_id()
    if cntl._accepted_stream_id:
        meta.stream_id = cntl._accepted_stream_id
        meta.stream_window = cntl._accepted_stream_window
    if cntl.failed:
        meta.error_code = cntl.error_code
        meta.error_text = cntl.error_text
        # negotiation facts still ride error responses (a lost accept
        # would make the client misread the peer as capability-less)
        sock.write(pack_frame(meta, IOBuf(), extra_meta=shm_extra))
        return
    try:
        payload = serialize_payload(response)
    except TypeError as e:
        meta.error_code = int(Errno.EINTERNAL)
        meta.error_text = f"response serialization failed: {e}"
        sock.write(pack_frame(meta, IOBuf(), extra_meta=shm_extra))
        return
    if cntl.response_compress_type:
        compressed = compress_mod.compress(payload.to_bytes(),
                                           cntl.response_compress_type)
        if compressed is not None:
            meta.compress_type = cntl.response_compress_type
            payload = IOBuf(compressed)
    # serialization (the last fallible step before prepare_send, whose
    # failure frame carries no descriptor either way) succeeded: the
    # attachment may stage into the ring now without leak risk
    _shm_describe()
    attachment = cntl.response_attachment
    if cntl.response_device_attachment is not None:
        from ..ici.endpoint import ici_enabled, local_domain_id, prepare_send
        if ici_enabled():
            meta.ici_domain = local_domain_id()
        try:
            tail = prepare_send(sock, meta, cntl.response_device_attachment,
                                timeout_s=5.0)
        except RuntimeError as e:
            meta.error_code = int(Errno.EOVERCROWDED)
            meta.error_text = str(e)
            sock.write(pack_frame(meta, IOBuf(), extra_meta=shm_extra))
            return
        if tail is not None:
            combined = IOBuf()
            combined.append_iobuf(attachment)
            combined.append_iobuf(tail)
            attachment = combined
    if cntl.span is not None:
        cntl.span.response_size = len(payload) + len(attachment)
    sock.write(pack_frame(meta, payload, attachment=attachment,
                          extra_meta=shm_extra + shm_desc))


def process_rpc_request(msg: RpcMessage, sock: Socket, server) -> None:
    meta = msg.meta
    cid = meta.correlation_id

    if _rpc_dump.dump_enabled():
        # sampled wire capture for rpc_replay (payload still carries the
        # attachment tail here — the dump is the original frame body)
        _rpc_dump.maybe_dump_request(meta, msg.payload.to_bytes())

    entry = server.find_method(meta.service_name, meta.method_name)
    if entry is None:
        known = meta.service_name in server.services
        _send_error(sock, cid,
                    Errno.ENOMETHOD if known else Errno.ENOSERVICE,
                    f"unknown {meta.service_name}.{meta.method_name}",
                    request_meta=meta)
        return

    # the compiled interceptor chain (ROADMAP item 1's FIFTH binding):
    # running check → admission → controller/attachment/ici/shm staging
    # → trace extract → deadline arm+shed all live in the chain's enter;
    # this lane body keeps only the protocol concerns (auth, user
    # interceptor, decompress/parse, user code)
    _enter, _settle = _chain_for(server, entry)

    def _send(cntl, response):
        # completion funnel — every response shape (sync return, async
        # finish, error escalation) settles through the chain exactly
        # once, then serializes on the classic wire builder
        _settle(cntl, response)
        _respond_wire(server, entry, cntl, response)

    cntl = _enter(msg, sock, _send)
    if cntl is None:
        return      # rejected/shed: the client is already answered

    # auth on first message of the connection (≈ Protocol::verify)
    auth = server.options.auth
    if auth is not None and sock.app_data is None:
        try:
            ok = auth.verify(meta.auth_data, cntl)
        except Exception:
            ok = False
        if not ok:
            cntl.set_failed(Errno.ERPCAUTH, "authentication failed")
            cntl.finish(None)
            return
        sock.app_data = "authed"

    # interceptor admission (≈ interceptor.h:26-36)
    interceptor = server.options.interceptor
    if interceptor is not None:
        try:
            verdict = interceptor(cntl)
        except Exception as e:
            verdict = (False, int(Errno.EINTERNAL), f"interceptor: {e}")
        ok = verdict[0] if isinstance(verdict, tuple) else bool(verdict)
        if not ok:
            code = verdict[1] if isinstance(verdict, tuple) else Errno.EREJECT
            text = verdict[2] if isinstance(verdict, tuple) and \
                len(verdict) > 2 else "rejected"
            cntl.set_failed(code, text)
            cntl.finish(None)
            return

    # payload → request object.  Raw methods consume the payload as-is:
    # a single-block buffer (the native ingest shape) passes through as
    # a zero-copy view instead of a to_bytes materialization.
    if entry.raw_fn is not None and not meta.compress_type:
        raw, _ = msg.payload.as_contiguous()
    else:
        raw = msg.payload.to_bytes()
    if meta.compress_type:
        raw = compress_mod.decompress(raw, meta.compress_type)
        if raw is None:
            cntl.set_failed(Errno.EREQUEST,
                            f"unsupported compress_type {meta.compress_type}")
            cntl.finish(None)
            return
    try:
        request = parse_payload(raw, entry.request_type)
    except Exception as e:
        cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
        cntl.finish(None)
        return

    # ---- user code (already on a fiber task) ----
    if entry.raw_fn is not None:
        # @raw_method on the full path (Python transport, or a request
        # carrying controller-tier features): same (payload, attachment)
        # handler contract, adapted from the parsed message
        att_buf = cntl.request_attachment
        # zero-copy attachment view: single-block buffers (native
        # ingest, shm descriptors) materialize nothing here
        att = att_buf.as_contiguous()[0] if len(att_buf) else None
        try:
            out = entry.raw_fn(memoryview(raw), att)
            resp, ratt = out if type(out) is tuple else (out, None)
            if not isinstance(resp, (bytes, bytearray, memoryview)):
                raise TypeError(
                    f"raw method returned {type(resp).__name__}, "
                    "expected bytes or (bytes, bytes)")
        except Exception as e:
            LOG.exception("raw method %s failed", entry.status.full_name)
            cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
            cntl.finish(None)
            return
        if ratt is not None and len(ratt):
            cntl.response_attachment.append_user_data(ratt)
        cntl.finish(resp)
        return
    try:
        with inherit_deadline(cntl):
            response = entry.fn(cntl, request)
    except Exception as e:
        LOG.exception("method %s raised", entry.status.full_name)
        cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.finish(None)
        return
    if cntl.is_async:
        return          # user owns completion via cntl.finish(resp)
    cntl.finish(response)
