"""Read-mostly data with contention-free reads.

Capability parity with DoublyBufferedData
(/root/reference/src/butil/containers/doubly_buffered_data.h:56): readers
never touch a shared mutex; writers pay the cost.  Backs load-balancer
server lists where SelectServer runs per-RPC.

Fresh design for CPython: attribute loads of an object reference are atomic
under the GIL, so the read path is a single snapshot load (even cheaper than
the reference's TLS-mutex scheme).  Writers copy-modify-swap under a writer
lock; the old snapshot stays alive until the last reader drops it (GC), which
is exactly the RCU guarantee the reference's fg/bg flip provides.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class DoublyBufferedData(Generic[T]):
    def __init__(self, initial: T,
                 copier: Optional[Callable[[T], T]] = None):
        """``copier`` clones the snapshot for modification; defaults to
        ``copy.deepcopy`` so nested containers are isolated from live
        readers.  Pass a cheaper copier (e.g. ``list.copy``) when the
        value is flat and modify-rate matters."""
        self._snapshot: T = initial
        self._copier = copier or copy.deepcopy
        self._writer_lock = threading.Lock()
        self.modify_count = 0

    def read(self) -> T:
        """Lock-free snapshot. The returned object must be treated as
        immutable by callers (same contract as reference ScopedPtr reads)."""
        return self._snapshot

    def modify(self, fn: Callable[[T], Optional[bool]]) -> bool:
        """Apply ``fn`` to a private deep copy and atomically publish it.
        ``fn`` returning False aborts the publish (mirrors the reference's
        ``Modify`` returning 0 => unchanged)."""
        with self._writer_lock:
            new = self._copier(self._snapshot)
            ret = fn(new)
            if ret is False:
                return False
            self._snapshot = new
            self.modify_count += 1
            return True

    def modify_with_new(self, new_value: T) -> None:
        with self._writer_lock:
            self._snapshot = new_value
            self.modify_count += 1
