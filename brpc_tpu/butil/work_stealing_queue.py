"""WorkStealingQueue — per-worker deque with owner push/pop and
foreign steal.

≈ /root/reference/src/bthread/work_stealing_queue.h: the owner pushes
and pops at the BOTTOM (LIFO — cache-hot continuation runs first),
thieves steal from the TOP (FIFO — oldest work migrates).  The
reference gets lock-freedom from atomics; under the GIL a short lock
gives the same semantics with the same interface, and the scheduler
layering (local queue first, steal on empty) is preserved.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional, Tuple


class WorkStealingQueue:
    __slots__ = ("_dq", "_lock", "_cap")

    def __init__(self, capacity: int = 4096):
        self._dq: deque = deque()
        self._lock = threading.Lock()
        self._cap = capacity

    def push(self, item: Any) -> bool:
        """Owner side; False when full (caller overflows to the shared
        queue)."""
        with self._lock:
            if len(self._dq) >= self._cap:
                return False
            self._dq.append(item)
            return True

    def pop(self) -> Tuple[bool, Optional[Any]]:
        """Owner side: newest item (LIFO)."""
        with self._lock:
            if self._dq:
                return True, self._dq.pop()
            return False, None

    def steal(self) -> Tuple[bool, Optional[Any]]:
        """Thief side: oldest item (FIFO)."""
        with self._lock:
            if self._dq:
                return True, self._dq.popleft()
            return False, None

    def __len__(self) -> int:
        return len(self._dq)
