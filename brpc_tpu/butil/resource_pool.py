"""Versioned-id slot pools.

Capability parity with the reference's ResourcePool/ObjectPool
(/root/reference/src/butil/resource_pool.h:22): objects addressable by a
compact integer id where the id embeds a *version*, so a stale id held by a
racing party safely resolves to "gone" instead of use-after-free.  This is
the mechanism behind SocketId and call correlation ids (see fiber.versioned_id).

Fresh design: a growable slot table + LIFO free list guarded by a lock (the
GIL makes fine-grained TLS free lists pointless in Python; the native C++
engine provides the contended-path fast pool).  Ids are 64-bit:
``(version << 32) | slot_index``.  Versions bump on every release, so each
slot survives 2^32 reuses before wrapping.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")

_SLOT_BITS = 32
_SLOT_MASK = (1 << _SLOT_BITS) - 1

INVALID_ID = 0xFFFFFFFFFFFFFFFF


def id_slot(rid: int) -> int:
    return rid & _SLOT_MASK


def id_version(rid: int) -> int:
    return rid >> _SLOT_BITS


def make_id(version: int, slot: int) -> int:
    return (version << _SLOT_BITS) | slot


class ResourcePool(Generic[T]):
    """Slot pool with versioned ids.

    - :meth:`acquire` -> (id, obj): takes a free slot (or grows), constructs
      via the factory, returns the versioned id.
    - :meth:`address` -> obj | None: resolves an id iff the version matches
      the slot's live version (stale ids resolve to None).
    - :meth:`release`: invalidates the id (bumps version) and recycles the
      slot. Safe against double-release of a stale id.
    """

    def __init__(self, factory: Optional[Callable[[], T]] = None):
        self._factory = factory
        self._lock = threading.Lock()
        self._objs: List[Optional[T]] = []
        self._versions: List[int] = []
        self._free: List[int] = []
        self.live_count = 0

    def acquire(self, obj: Optional[T] = None) -> Tuple[int, T]:
        if obj is None:
            if self._factory is None:
                raise ValueError("no object given and no factory configured")
            obj = self._factory()
        with self._lock:
            if self._free:
                slot = self._free.pop()
                self._objs[slot] = obj
            else:
                slot = len(self._objs)
                self._objs.append(obj)
                # version starts at 1 so id 0 is never live with version 0
                self._versions.append(1)
            self.live_count += 1
            return make_id(self._versions[slot], slot), obj

    def address(self, rid: int) -> Optional[T]:
        slot = rid & _SLOT_MASK
        version = rid >> _SLOT_BITS
        # Reads tolerate racing release: worst case we return an object that
        # is being released concurrently — same contract as the reference
        # (address_resource returns the slot; Socket layers re-check health).
        try:
            if self._versions[slot] == version:
                return self._objs[slot]
        except IndexError:
            pass
        return None

    def release(self, rid: int) -> bool:
        slot = rid & _SLOT_MASK
        version = rid >> _SLOT_BITS
        with self._lock:
            try:
                if self._versions[slot] != version:
                    return False
            except IndexError:
                return False
            self._versions[slot] += 1
            self._objs[slot] = None
            self._free.append(slot)
            self.live_count -= 1
            return True

    def __len__(self) -> int:
        return self.live_count

    def live_items(self) -> List[Tuple[int, T]]:
        """Snapshot of (id, obj) for live slots (introspection pages)."""
        out: List[Tuple[int, T]] = []
        with self._lock:
            for slot, obj in enumerate(self._objs):
                if obj is not None:
                    out.append((make_id(self._versions[slot], slot), obj))
        return out


class ObjectPool(Generic[T]):
    """Simple recycling pool without ids (≈ butil::ObjectPool,
    /root/reference/src/butil/object_pool_inl.h). ``get``/``put`` reuse
    instances; the factory constructs on miss, ``reset`` (if provided)
    scrubs recycled instances."""

    def __init__(
        self,
        factory: Callable[[], T],
        reset: Optional[Callable[[T], None]] = None,
        max_cached: int = 1024,
    ):
        self._factory = factory
        self._reset = reset
        self._free: List[T] = []
        self._lock = threading.Lock()
        self._max_cached = max_cached
        self.hits = 0
        self.misses = 0

    def get(self) -> T:
        with self._lock:
            if self._free:
                self.hits += 1
                return self._free.pop()
        self.misses += 1
        return self._factory()

    def put(self, obj: T) -> None:
        if self._reset is not None:
            self._reset(obj)
        with self._lock:
            if len(self._free) < self._max_cached:
                self._free.append(obj)
