"""Zero-copy chained buffer — the data currency of the whole stack.

Capability parity with the reference's ``butil::IOBuf``
(/root/reference/src/butil/iobuf.h:61): a chain of refcounted block
references supporting O(1) append/cut/share without copying payload bytes.

Fresh design notes (not a port):

- Blocks are refcounted by the Python GC instead of manual atomics; a
  ``BlockRef`` is a ``[block, offset, length]`` triple and IOBufs share the
  underlying storage freely.
- The block allocator is a pluggable :class:`BlockPool`.  The default pool
  hands out 8KB host ``bytearray`` slabs with a free list; the ICI transport
  plugs in a DMA/HBM-backed pool with the same interface — the lesson of the
  reference retrofitting ``rdma/block_pool`` (SURVEY.md §5.8) is baked in
  from day 1.
- Sequential small appends from one thread pack into a thread-local open
  block, mirroring the reference's TLS block cache
  (/root/reference/src/butil/iobuf.cpp:297-306).
"""

from __future__ import annotations

import errno as _errno_mod
import sys
import threading
import weakref
from collections import deque

_errno_EAGAIN = _errno_mod.EAGAIN
from typing import Iterable, List, Optional, Tuple, Union

from . import copy_audit as _audit

DEFAULT_BLOCK_SIZE = 8192

# file-backed blocks (shm-ring slots) at/above this size leave a TCP
# socket via os.sendfile instead of a userspace read of the mapping
SENDFILE_MIN = 64 * 1024


def _is_tls(sock) -> bool:
    try:
        import ssl as _ssl
    except ImportError:             # pragma: no cover
        return False
    return isinstance(sock, _ssl.SSLSocket) or isinstance(
        sock, getattr(_ssl, "SSLObject", ()))


class Block:
    """A refcounted storage slab. ``data`` is writable (bytearray) for pool
    blocks or an arbitrary buffer for user-attached (zero-copy) data.
    ``size`` is the filled prefix; only the filled prefix may be referenced.
    """

    __slots__ = ("data", "size", "capacity", "pool", "file_ref",
                 "__weakref__")

    def __init__(self, data, size: int, pool: Optional["BlockPool"] = None,
                 file_ref: Optional[Tuple[int, int]] = None):
        self.data = data
        self.size = size
        self.capacity = len(data)
        self.pool = pool
        # (fd, base_offset): the block aliases a file-backed mapping
        # (shm-ring slot) — the TCP spill path ships it via os.sendfile
        self.file_ref = file_ref

    @property
    def left_space(self) -> int:
        return self.capacity - self.size

    if sys.version_info >= (3, 12):
        def __buffer__(self, flags: int) -> memoryview:
            # PEP 688: the Block itself is the buffer exporter, so every
            # view handed out keeps the BLOCK (not just its bytearray)
            # alive — the recycling finalizer cannot fire while
            # zero-copy views exist anywhere (write queues, the native
            # engine's pinned Py_buffers).
            return memoryview(self.data)

        def view(self, offset: int, length: int) -> memoryview:
            # no caching: a Block-held memoryview(self) would be a
            # reference cycle, deferring recycling to the cycle
            # collector
            return memoryview(self)[offset : offset + length]
    else:
        def view(self, offset: int, length: int) -> memoryview:
            # pre-PEP-688 interpreters cannot export a buffer from a
            # plain class: views alias the storage directly.  A view's
            # chain then keeps only the bytearray alive, NOT the Block
            # — so storage recycling is disabled on these interpreters
            # (HostBlockPool.allocate) to keep the no-aliasing
            # invariant; only performance degrades.
            return memoryview(self.data)[offset : offset + length]


class BlockPool:
    """Block allocator interface. Subclasses: HostBlockPool (bytearrays),
    and the transport layer's device pools (HBM slabs) share this interface.

    Recycling is tied to object lifetime (GC), never manual: a block's
    storage returns to the pool only when no IOBuf/ref can reach it anymore,
    so recycled slabs can never alias live zero-copy views.
    """

    def allocate(self, capacity: int = DEFAULT_BLOCK_SIZE) -> Block:
        raise NotImplementedError


class HostBlockPool(BlockPool):
    """Free-listed host memory pool. Thread-safe.

    Storage recycling rides a ``weakref.finalize`` on the Block: when the
    last reference (IOBuf ref / TLS open-block slot) drops, the bytearray
    goes back on the free list.  NOTE: memoryviews obtained from
    ``backing_views()`` are only valid while the owning IOBuf is alive.
    """

    def __init__(self, block_size: int = DEFAULT_BLOCK_SIZE, max_cached: int = 64):
        self.block_size = block_size
        self._free: deque = deque()
        # large read slabs (adaptive socket reads) recycle through
        # size-class free lists — a fresh bytearray(512KB) is a 512KB
        # memset per recv otherwise, the top cost in the echo profile
        self._free_large: dict = {}
        self._large_cached = 0
        self._max_large_cached_bytes = 64 << 20
        self._lock = threading.Lock()
        self._max_cached = max_cached
        self.allocated = 0  # stats
        self.reused = 0

    def allocate(self, capacity: int = 0) -> Block:
        capacity = capacity or self.block_size
        data = None
        if capacity == self.block_size:
            with self._lock:
                if self._free:
                    data = self._free.popleft()
                    self.reused += 1
        elif capacity > self.block_size:
            with self._lock:
                lst = self._free_large.get(capacity)
                if lst:
                    data = lst.pop()
                    self._large_cached -= capacity
                    self.reused += 1
        if data is None:
            self.allocated += 1
            data = bytearray(capacity)
        blk = Block(data, 0, self)
        if capacity >= self.block_size and sys.version_info >= (3, 12):
            # recycling is safe only when views export the BLOCK's
            # buffer (PEP 688, Block.view): otherwise a recycled slab
            # could be rewritten while an old view still aliases it
            weakref.finalize(blk, self._recycle, data)
        return blk

    def _recycle(self, data: bytearray) -> None:
        n = len(data)
        with self._lock:
            if n == self.block_size:
                if len(self._free) < self._max_cached:
                    self._free.append(data)
            elif self._large_cached + n <= self._max_large_cached_bytes:
                self._free_large.setdefault(n, []).append(data)
                self._large_cached += n


_default_pool = HostBlockPool()


class _TLS(threading.local):
    def __init__(self):
        self.open_block: Optional[Block] = None


_tls = _TLS()


def _sharable_block(min_space: int = 1) -> Block:
    """Thread-local open block new appends pack into (TLS block cache)."""
    b = _tls.open_block
    if b is None or b.left_space < min_space:
        b = _default_pool.allocate()
        _tls.open_block = b
    return b


def default_block_pool() -> HostBlockPool:
    return _default_pool


BytesLike = Union[bytes, bytearray, memoryview, str]


class IOBuf:
    """Non-contiguous zero-copy buffer: a deque of block references.

    O(1) for append of another IOBuf (ref sharing), cheap cut/pop at either
    end (ref arithmetic only).  Payload bytes are copied only on explicit
    materialization (``bytes(buf)`` / :meth:`copy_to`).
    """

    __slots__ = ("_refs", "_size", "_pool", "_open_block")

    def __init__(self, data: Optional[BytesLike] = None,
                 pool: Optional[BlockPool] = None):
        self._refs: deque = deque()  # of [block, offset, length]
        self._size = 0
        # Optional injected pool (e.g. a DMA/HBM-registered pool from the
        # device transport). None => thread-shared default host pool.
        self._pool = pool
        self._open_block: Optional[Block] = None
        if data is not None:
            self.append(data)

    def _write_block(self, min_space: int = 1) -> Block:
        if self._pool is None:
            return _sharable_block(min_space)
        b = self._open_block
        if b is None or b.left_space < min_space:
            b = self._pool.allocate()
            self._open_block = b
        return b

    # ---- introspection ----

    def __len__(self) -> int:
        return self._size

    @property
    def size(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

    @property
    def backing_block_count(self) -> int:
        return len(self._refs)

    def backing_views(self) -> List[memoryview]:
        """Scatter-gather list for vectored IO (≈ IOBuf::backing_block)."""
        return [blk.view(off, ln) for blk, off, ln in self._refs]

    # ---- building ----

    def clear(self) -> None:
        self._refs.clear()
        self._size = 0

    def append(self, data: Union[BytesLike, "IOBuf"]) -> None:
        if len(data) > DEFAULT_BLOCK_SIZE:
            if isinstance(data, bytes):
                # large immutable payloads attach zero-copy instead of
                # being chopped into pool blocks (bytes never mutate)
                self.append_user_data(data)
                return
            if isinstance(data, memoryview) and data.readonly \
                    and data.c_contiguous \
                    and isinstance(data.obj, bytes):
                # a large view EXPORTED BY bytes is as safe as bytes:
                # no writer exists anywhere (readonly alone is not
                # enough — it blocks writes through the view, not
                # through a bytearray/ndarray owner, and append's
                # contract is copy semantics).  Response serialization
                # of sliced bytes payloads was paying a block-by-block
                # copy here (ISSUE 6 satellite); callers that own a
                # no-mutate contract for OTHER storage attach it
                # explicitly via append_user_data.
                self.append_user_data(
                    data if data.format == "B" else data.cast("B"))
                return
        self._append_copy(data)

    def _append_copy(self, data: Union[BytesLike, "IOBuf"]) -> None:
        if isinstance(data, IOBuf):
            self.append_iobuf(data)
            return
        if isinstance(data, str):
            data = data.encode("utf-8")
        n = len(data)
        if n == 0:
            return
        if _audit.enabled and n >= _audit.AUDIT_FLOOR:
            _audit.record("ingest", n)
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        pos = 0
        while pos < n:
            blk = self._write_block()
            take = min(n - pos, blk.left_space)
            start = blk.size
            blk.data[start : start + take] = mv[pos : pos + take]
            blk.size += take
            self._append_ref(blk, start, take)
            pos += take
        self._size += n

    def append_user_data(self, data, file_ref=None) -> None:
        """Zero-copy attach of an external buffer (≈ append_user_data,
        /root/reference/src/butil/iobuf.h — user block, not pool-owned).
        The caller must not mutate ``data`` afterwards.  ``file_ref`` =
        (fd, base_offset) marks a file-backed mapping (shm-ring slot)
        eligible for the sendfile spill in :meth:`cut_into_socket`."""
        n = len(data)
        if n == 0:
            return
        blk = Block(data, n, None, file_ref=file_ref)
        self._refs.append([blk, 0, n])
        self._size += n

    def prepend_user_data(self, data) -> None:
        """Zero-copy attach of an external buffer at the FRONT (control
        frames piggybacking ahead of a queued payload frame)."""
        n = len(data)
        if n == 0:
            return
        blk = Block(data, n, None)
        self._refs.appendleft([blk, 0, n])
        self._size += n

    def append_iobuf(self, other: "IOBuf") -> None:
        """Share other's refs — O(#blocks), zero payload copies."""
        for blk, off, ln in other._refs:
            self._append_ref(blk, off, ln)
        self._size += other._size

    def push_back(self, byte: int) -> None:
        blk = self._write_block()
        blk.data[blk.size] = byte
        self._append_ref(blk, blk.size, 1)
        blk.size += 1
        self._size += 1

    def _append_ref(self, blk: Block, off: int, ln: int) -> None:
        if self._refs:
            last = self._refs[-1]
            if last[0] is blk and last[1] + last[2] == off:
                last[2] += ln  # merge contiguous refs in the same block
                return
        self._refs.append([blk, off, ln])

    # ---- consuming ----

    def pop_front(self, n: int) -> int:
        n = min(n, self._size)
        left = n
        while left > 0:
            ref = self._refs[0]
            if ref[2] <= left:
                left -= ref[2]
                self._refs.popleft()
            else:
                ref[1] += left
                ref[2] -= left
                left = 0
        self._size -= n
        return n

    def pop_back(self, n: int) -> int:
        n = min(n, self._size)
        left = n
        while left > 0:
            ref = self._refs[-1]
            if ref[2] <= left:
                left -= ref[2]
                self._refs.pop()
            else:
                ref[2] -= left
                left = 0
        self._size -= n
        return n

    def cutn(self, n: int, out: Optional["IOBuf"] = None) -> "IOBuf":
        """Cut the first n bytes into a new (or provided) IOBuf, sharing
        blocks (zero-copy) — ≈ IOBuf::cutn."""
        if out is None:
            out = IOBuf()
        n = min(n, self._size)
        left = n
        while left > 0:
            ref = self._refs[0]
            if ref[2] <= left:
                out._append_ref(ref[0], ref[1], ref[2])
                left -= ref[2]
                self._refs.popleft()
            else:
                out._append_ref(ref[0], ref[1], left)
                ref[1] += left
                ref[2] -= left
                left = 0
        out._size += n
        self._size -= n
        return out

    def cut_into(self, writer) -> int:
        """Write to a writable with ``write(view)`` semantics; returns bytes
        written and consumes exactly that many.  Handles short writes: stops
        at the first partial/refused write, leaving the tail intact."""
        total = 0
        for v in self.backing_views():
            n = writer.write(v)
            if n is None:          # e.g. io.BufferedWriter contract
                n = len(v)
            total += n
            if n < len(v):
                break
        self.pop_front(total)
        return total

    # ---- reading without consuming ----

    def as_contiguous(self) -> Tuple[memoryview, bool]:
        """The whole buffer as ONE contiguous view: ``(view, copied)``.
        Single-block buffers (the native ingest shape) return a
        zero-copy view into the backing block; chained buffers gather
        once (the audited scatter-gather join) — the receive-side
        landing path (attachment → numpy → device) uses this instead of
        ``to_bytes`` so the common case materializes nothing."""
        if len(self._refs) == 1:
            blk, off, ln = self._refs[0]
            if off == 0 and ln == blk.size \
                    and isinstance(blk.data, memoryview):
                # full-span user block: hand back the ORIGINAL buffer
                # object, not a fresh slice — identity survives handler
                # round trips (the shm echo-by-reference check compares
                # block storage by identity)
                return blk.data, False
            return blk.view(off, ln), False
        if _audit.enabled and self._size >= _audit.AUDIT_FLOOR:
            _audit.record("gather", self._size)
        out = bytearray(self._size)
        pos = 0
        for blk, off, ln in self._refs:
            out[pos:pos + ln] = blk.view(off, ln)
            pos += ln
        return memoryview(out), True

    def fetch(self, n: int) -> bytes:
        """Peek first n bytes (copies n bytes, does not consume)."""
        n = min(n, self._size)
        if _audit.enabled and n >= _audit.AUDIT_FLOOR:
            _audit.record("materialize", n)
        out = bytearray(n)
        pos = 0
        for blk, off, ln in self._refs:
            if pos >= n:
                break
            take = min(ln, n - pos)
            out[pos : pos + take] = blk.view(off, take)
            pos += take
        return bytes(out)

    def fetch1(self) -> Optional[int]:
        if not self._refs:
            return None
        blk, off, _ = self._refs[0]
        return blk.data[off]

    def copy_to(self, n: Optional[int] = None, pos: int = 0) -> bytes:
        if n is None:
            n = self._size - pos
        if pos:
            tmp = bytearray(self.fetch(pos + n))
            return bytes(tmp[pos : pos + n])
        return self.fetch(n)

    def to_bytes(self) -> bytes:
        return self.fetch(self._size)

    def __bytes__(self) -> bytes:
        return self.to_bytes()

    def __eq__(self, other) -> bool:
        if isinstance(other, (bytes, bytearray)):
            return self._size == len(other) and self.to_bytes() == bytes(other)
        if isinstance(other, IOBuf):
            return self._size == other._size and self.to_bytes() == other.to_bytes()
        return NotImplemented

    def __repr__(self) -> str:
        head = self.fetch(32)
        return f"IOBuf(size={self._size}, blocks={len(self._refs)}, head={head!r})"

    # ---- fd / socket integration ----

    def cut_into_socket(self, sock, max_bytes: Optional[int] = None) -> int:
        """Vectored send (≈ cut_into_file_descriptor,
        /root/reference/src/butil/iobuf.h:160). Consumes what was sent.

        A file-backed block (shm-ring slot spilling onto the TCP lane)
        at the queue head ships via ``os.sendfile`` — the kernel pulls
        straight from the page cache/tmpfs pages, never re-reading the
        mapping through userspace.  Never on a TLS socket: sendfile
        writes beneath the SSL record layer (plaintext on the wire);
        those blocks take the encrypted send path below."""
        if self._refs:
            blk, off, ln = self._refs[0]
            if blk.file_ref is not None and ln >= SENDFILE_MIN \
                    and not _is_tls(sock):
                import os as _os
                fd, base = blk.file_ref
                want = ln if max_bytes is None else min(ln, max_bytes)
                try:
                    sent = _os.sendfile(sock.fileno(), fd, base + off,
                                        want)
                except BlockingIOError:
                    raise
                except OSError:
                    pass        # no sendfile on this fd/sandbox: fall
                                # through to the sendmsg view path
                else:
                    self.pop_front(sent)
                    return sent
        views = self.backing_views()
        if max_bytes is not None:
            clipped, acc = [], 0
            for v in views:
                if acc + len(v) > max_bytes:
                    v = v[: max_bytes - acc]
                clipped.append(v)
                acc += len(v)
                if acc >= max_bytes:
                    break
            views = clipped
        if not views:
            return 0
        try:
            sent = sock.sendmsg(views)
        except NotImplementedError:
            # TLS sockets have no scatter-gather send; SSLWantWrite maps
            # to the EAGAIN contract the write path already understands
            import ssl as _ssl
            try:
                sent = sock.send(views[0])
            except (_ssl.SSLWantWriteError, _ssl.SSLWantReadError):
                raise BlockingIOError(_errno_EAGAIN, "ssl wants io")
        self.pop_front(sent)
        return sent


class IOPortal(IOBuf):
    """IOBuf that can read from sockets into pool blocks
    (≈ butil::IOPortal)."""

    __slots__ = ()

    def append_from_socket(self, sock, max_bytes: int = 65536) -> int:
        """recv_into a fresh/open tail region. Returns bytes read
        (0 = EOF, raises BlockingIOError if nonblocking and empty).

        Large reads get a dedicated block of ``max_bytes`` so a 512KB
        gulp is ONE recv into one slab, not 64 pool-block nibbles — the
        syscall-amortization the reference gets from readv into an
        IOPortal's block chain (src/butil/iobuf.cpp read path).  The
        current tail block is reused while it still has meaningful room,
        so trickling traffic on a connection with a large avg-msg-size
        EMA doesn't churn a fresh large slab per recv."""
        if max_bytes > DEFAULT_BLOCK_SIZE:
            # Only a DEDICATED large slab (capacity > pool block size) may
            # be reused: a pool-sized tail could be the thread-local shared
            # block, which another thread's appends write into concurrently.
            tail = self._refs[-1][0] if self._refs else None
            if tail is not None and tail.pool is not None \
                    and tail.capacity > DEFAULT_BLOCK_SIZE \
                    and tail.left_space >= max_bytes // 4:
                blk = tail
            else:
                blk = (self._pool or default_block_pool()).allocate(max_bytes)
        else:
            blk = self._write_block(min_space=512)
        space = min(blk.left_space, max_bytes)
        nread = sock.recv_into(blk.view(blk.size, space), space)
        if nread > 0:
            self._append_ref(blk, blk.size, nread)
            blk.size += nread
            self._size += nread
        return nread


class IOBufAppender:
    """Amortized fast appender for many small writes (≈ IOBufAppender)."""

    def __init__(self, buf: Optional[IOBuf] = None):
        self.buf = buf if buf is not None else IOBuf()
        self._pending: List[bytes] = []
        self._pending_size = 0

    def append(self, data: BytesLike) -> None:
        if isinstance(data, str):
            data = data.encode("utf-8")
        self._pending.append(bytes(data))
        self._pending_size += len(data)
        if self._pending_size >= DEFAULT_BLOCK_SIZE:
            self.flush()

    def flush(self) -> IOBuf:
        if self._pending:
            self.buf.append(b"".join(self._pending))
            self._pending.clear()
            self._pending_size = 0
        return self.buf


class IOBufReader:
    """Sequential reader over an IOBuf without consuming it.

    Keeps a (ref_index, offset_in_ref) cursor so reading a buffer in chunks
    is O(total_bytes), not O(n^2).  The underlying IOBuf must not be
    mutated while a reader is in use.
    """

    def __init__(self, buf: IOBuf):
        self._buf = buf
        self._pos = 0
        self._ref_idx = 0
        self._ref_off = 0

    def read(self, n: int) -> bytes:
        n = min(n, self._buf._size - self._pos)
        if n <= 0:
            return b""
        out = bytearray(n)
        got = 0
        refs = self._buf._refs
        while got < n:
            blk, off, ln = refs[self._ref_idx]
            avail = ln - self._ref_off
            take = min(avail, n - got)
            src = blk.view(off + self._ref_off, take)
            out[got : got + take] = src
            got += take
            self._ref_off += take
            if self._ref_off >= ln:
                self._ref_idx += 1
                self._ref_off = 0
        self._pos += n
        return bytes(out)

    def remaining(self) -> int:
        return self._buf.size - self._pos


class LazyAttachmentsMixin:
    """Lazily-constructed request/response attachment IOBufs for the
    client and server controllers.  A sync unary call usually replaces
    both attachments, so eager construction cost ~2 IOBufs/call on the
    echo hot path.  Subclasses declare ``_req_att``/``_resp_att`` in
    their ``__slots__`` and initialize both to None."""

    __slots__ = ()

    @property
    def request_attachment(self) -> "IOBuf":
        a = self._req_att
        if a is None:
            a = self._req_att = IOBuf()
        return a

    @request_attachment.setter
    def request_attachment(self, v: "IOBuf") -> None:
        self._req_att = v

    @property
    def response_attachment(self) -> "IOBuf":
        a = self._resp_att
        if a is None:
            a = self._resp_att = IOBuf()
        return a

    @response_attachment.setter
    def response_attachment(self, v: "IOBuf") -> None:
        self._resp_att = v
