"""CRC32-C (Castagnoli) — protocol checksums & consistent hashing input
(≈ /root/reference/src/butil/crc32c.cc, which uses SSE4.2; bulk payload
checksumming on device lives in brpc_tpu.ops.checksum).

Table-driven implementation, polynomial 0x1EDC6F41 (reflected 0x82F63B78).
"""

from __future__ import annotations

_POLY = 0x82F63B78


def _make_table():
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _make_table()


def crc32c_extend(crc: int, data) -> int:
    """Extend a running crc with data (matches the standard CRC32C)."""
    c = crc ^ 0xFFFFFFFF
    tbl = _TABLE
    for b in bytes(data):
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def crc32c(data) -> int:
    return crc32c_extend(0, data)


# murmurhash-style 64-bit mix used by consistent hashing when a fast
# non-crypto hash is wanted (≈ third_party/murmurhash3 usage in hasher.cpp)
def fmix64(k: int) -> int:
    mask = (1 << 64) - 1
    k &= mask
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & mask
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & mask
    k ^= k >> 33
    return k


def hash_bytes64(data: bytes, seed: int = 0) -> int:
    """64-bit hash of bytes built from fmix64 over 8-byte words."""
    h = seed ^ (len(data) << 1)
    for i in range(0, len(data), 8):
        word = int.from_bytes(data[i : i + 8], "little")
        h = fmix64(h ^ word)
    return fmix64(h)
