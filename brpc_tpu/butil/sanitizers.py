"""Runtime sanitizers: stall watchdog + lock-order (deadlock) detector.

The reference ships no custom race detector either — it vendors TSAN/
valgrind/ASAN annotations and argues lock-free correctness in comments
(SURVEY §5.2, /root/reference/src/butil/third_party/dynamic_annotations,
src/bthread/butex.cpp:188-240).  The Python-native analogues here are
runtime diagnostics instead of compile-time instrumentation:

- **StallWatchdog** (flag ``stall_watchdog_s``): long blocking waits
  register themselves; a timer sweep logs every thread's stack ONCE per
  stall when a registered wait exceeds the threshold — the "why is my
  RPC stuck" tool, usable in production (zero cost per wait beyond a
  dict insert, and only when the flag is on).
- **DebugLock** (``debug_lock_order``): a Lock wrapper that records the
  held→acquiring edge per thread into a global lock-order graph and
  logs a *potential deadlock* the first time an ABBA cycle appears —
  catches lock-inversion bugs even when the timing never actually
  deadlocks (what TSAN's lock-order checker does for the reference's
  CI builds).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

from .flags import define_flag, get_flag
from .logging_util import LOG

define_flag("stall_watchdog_s", 0.0,
            "log all thread stacks when a registered blocking wait "
            "exceeds this many seconds (0 = off)",
            validator=lambda v: float(v) >= 0)
define_flag("debug_lock_order", False,
            "record the lock-order graph on DebugLock acquisitions and "
            "warn on cycles (potential ABBA deadlocks)",
            validator=lambda v: True)


# -- stall watchdog ---------------------------------------------------------

_waits: Dict[int, Tuple[str, float, int]] = {}   # id -> (what, since, tid)
_waits_lock = threading.Lock()
_wait_seq = 0
_reported: Set[int] = set()
_sweeper_started = False


def watchdog_enabled() -> bool:
    return float(get_flag("stall_watchdog_s", 0.0)) > 0


class watched_wait:
    """Context manager wrapping a blocking wait so the watchdog can see
    it: ``with watched_wait("butex"): cond.wait_for(...)``."""

    __slots__ = ("what", "_id")

    def __init__(self, what: str):
        self.what = what
        self._id = 0

    def __enter__(self):
        global _wait_seq
        _ensure_sweeper()
        with _waits_lock:
            _wait_seq += 1
            self._id = _wait_seq
            _waits[self._id] = (self.what, time.monotonic(),
                                threading.get_ident())
        return self

    def __exit__(self, *exc):
        with _waits_lock:
            _waits.pop(self._id, None)
            _reported.discard(self._id)
        return False


def _dump_stacks(reason: str) -> str:
    out: List[str] = [reason]
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.append("".join(traceback.format_stack(frame)))
    text = "\n".join(out)
    LOG.error("%s", text)
    return text


def check_stalls(now: Optional[float] = None) -> int:
    """One sweep (also called by tests): report waits older than the
    threshold; each wait is reported once, and one sweep emits ONE
    all-thread stack dump no matter how many waits crossed the
    threshold together (a single hung dependency can strand hundreds).
    Returns #newly reported."""
    limit = float(get_flag("stall_watchdog_s", 0.0))
    if limit <= 0:
        return 0
    now = time.monotonic() if now is None else now
    with _waits_lock:
        stuck = [(wid, what, since) for wid, (what, since, _t)
                 in _waits.items()
                 if now - since > limit and wid not in _reported]
        for wid, _, _ in stuck:
            _reported.add(wid)
    if stuck:
        lines = ", ".join(f"'{what}' blocked {now - since:.1f}s"
                          for _w, what, since in stuck[:20])
        _dump_stacks(f"STALL: {len(stuck)} wait(s) exceeded "
                     f"stall_watchdog_s={limit}: {lines}")
    return len(stuck)


_manual = False      # tests drive check_stalls() themselves


def _ensure_sweeper() -> None:
    global _sweeper_started
    if _sweeper_started or _manual or not watchdog_enabled():
        return
    _sweeper_started = True
    from ..fiber.timer_thread import global_timer_thread

    def sweep():
        try:
            if not _manual:
                check_stalls()
        finally:
            period = max(float(get_flag("stall_watchdog_s", 0.0)) / 2,
                         0.5)
            global_timer_thread().schedule(sweep, period)

    global_timer_thread().schedule(sweep, 0.5)


# -- lock-order detector ----------------------------------------------------

_order_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}        # held -> then-acquired
_warned_cycles: Set[Tuple[str, str]] = set()
_tls = threading.local()

# flag cache: DebugLock now sits on hot paths (the fiber ExecutionQueue
# backing the socket write pump), so the per-acquire check must be one
# list read, not a flags-table lookup (same pattern as admission's
# CoDel cache)
from .flags import watch_flag as _watch_flag

_order_live = [bool(get_flag("debug_lock_order", False))]
_watch_flag("debug_lock_order",
            lambda v: _order_live.__setitem__(0, bool(v)))

# warning-count bvar on /vars (satellite: the count was test-only).
# Registered at module import below, with a DebugLock-construction
# retry hook: if an import-order edge ever defers the bvar package,
# the next DebugLock re-attempts instead of latching the var off.
_warn_var = None
_warn_var_lock = threading.Lock()


def _ensure_warning_var() -> None:
    global _warn_var
    with _warn_var_lock:
        if _warn_var is not None:
            # a test-scoped registry wipe (bvar
            # clear_registry_for_tests) un-exposes import-time vars
            # without telling them: re-expose on the next DebugLock
            # instead of latching the var off for the process's life
            try:
                from ..bvar.variable import find_exposed
                if find_exposed("sanitizer_lock_order_warnings") \
                        is not _warn_var:
                    _warn_var.expose("sanitizer_lock_order_warnings")
            except Exception:
                pass
            return
        try:
            from ..bvar.passive_status import PassiveStatus
            _warn_var = PassiveStatus(
                lambda: lock_order_warnings(),
                name="sanitizer_lock_order_warnings")
        except Exception:       # deferred: retried on next DebugLock
            pass


def _has_path(src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(_edges.get(node, ()))
    return False


class DebugLock:
    """threading.Lock with lock-order recording (under the
    ``debug_lock_order`` flag; a plain pass-through otherwise).

    Also a drop-in Condition backing: the fiber ExecutionQueue wires
    its queue lock through this class, so ABBA inversions between
    queue roles and application locks show up in the order graph."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        _ensure_warning_var()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if _order_live[0]:
            held: List[str] = getattr(_tls, "held", None) or []
            with _order_lock:
                for h in held:
                    if h == self.name:
                        continue
                    # adding h -> self; a pre-existing path self -> h
                    # closes an ABBA cycle.  Canonical (sorted) key:
                    # the same cycle warns once regardless of which
                    # order trips the detector
                    key = tuple(sorted((self.name, h)))
                    if _has_path(self.name, h) \
                            and key not in _warned_cycles:
                        _warned_cycles.add(key)
                        LOG.error(
                            "POTENTIAL DEADLOCK: lock order cycle "
                            "'%s' -> '%s' (both orders observed)\n%s",
                            h, self.name,
                            "".join(traceback.format_stack(limit=8)))
                    _edges.setdefault(h, set()).add(self.name)
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                held = getattr(_tls, "held", None)
                if held is None:
                    held = _tls.held = []
                held.append(self.name)
            return ok
        # flag off: pure pass-through — no TLS bookkeeping on hot paths
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        held = getattr(_tls, "held", None)
        if held and self.name in held:
            held.remove(self.name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._lock.locked()


def lock_order_warnings() -> int:
    """Number of distinct cycles warned so far (introspection/tests)."""
    with _order_lock:
        return len(_warned_cycles)


def reset_for_tests() -> None:
    """Also switches to manual sweeping: tests call check_stalls()
    deterministically instead of racing the background timer."""
    global _manual
    _manual = True
    with _order_lock:
        _edges.clear()
        _warned_cycles.clear()
    with _waits_lock:
        _waits.clear()
        _reported.clear()


_ensure_warning_var()
