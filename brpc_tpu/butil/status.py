"""Error status value (≈ /root/reference/src/butil/status.h) and the
framework-wide error codes (≈ /root/reference/src/brpc/errno.proto)."""

from __future__ import annotations

from enum import IntEnum
from typing import Optional


class Errno(IntEnum):
    """RPC error space — names mirror the reference's brpc/errno.proto so
    operators coming from the reference find the same vocabulary."""

    OK = 0
    # Framework errors (reference errno.proto values kept where they exist)
    ENOSERVICE = 1001      # service not found
    ENOMETHOD = 1002       # method not found
    EREQUEST = 1003        # bad request
    ERPCAUTH = 1004        # authentication failed
    ETOOMANYFAILS = 1005   # too many sub-channel failures (ParallelChannel)
    EPCHANFINISH = 1006    # ParallelChannel finished
    EBACKUPREQUEST = 1007  # backup request fired (internal)
    ERPCTIMEDOUT = 1008    # RPC deadline exceeded
    EFAILEDSOCKET = 1009   # socket broken during RPC
    EHTTP = 1010           # HTTP non-2xx
    EOVERCROWDED = 1011    # too many buffering bytes / queue full
    ERTMPPUBLISHABLE = 1012
    ERTMPCREATESTREAM = 1013
    EEOF = 1014            # stream EOF
    EUNUSED = 1015         # connection unused
    ESSL = 1016
    EH2RUNOUTSTREAMS = 1017
    EREJECT = 1018         # rejected by Interceptor / concurrency limiter
    # Client-side
    EINTERNAL = 2001
    ERESPONSE = 2002
    ELOGOFF = 2003         # server is stopping
    ELIMIT = 2004          # concurrent requests over max_concurrency
    ECLOSE = 2005
    EITP = 2007
    ELAMEDUCK = 2008       # server draining: re-resolve, no breaker
    #                        penalty (fail-fast retried on LB channels
    #                        like ELIMIT — the operability plane)
    # Additions for the TPU build
    EDEVICE = 3001         # device/ICI transport failure
    EMESH = 3002           # mesh membership/topology error
    ECANCELLED = 3003      # call cancelled via CallId


class Status:
    """Error code + message; falsy when not OK to allow `if not st:`."""

    __slots__ = ("code", "message")

    def __init__(self, code: int = 0, message: str = ""):
        self.code = int(code)
        self.message = message

    @staticmethod
    def ok() -> "Status":
        return Status(0, "")

    def is_ok(self) -> bool:
        return self.code == 0

    def __bool__(self) -> bool:
        return self.code == 0

    def set_error(self, code: int, message: str = "") -> "Status":
        self.code = int(code)
        self.message = message
        return self

    def reset(self) -> None:
        self.code = 0
        self.message = ""

    def error_str(self) -> str:
        if self.code == 0:
            return "OK"
        try:
            name = Errno(self.code).name
        except ValueError:
            name = str(self.code)
        return f"[{name}] {self.message}" if self.message else f"[{name}]"

    def __repr__(self) -> str:
        return f"Status({self.error_str()})"

    def __eq__(self, other) -> bool:
        if isinstance(other, Status):
            return self.code == other.code
        if isinstance(other, int):
            return self.code == other
        return NotImplemented
