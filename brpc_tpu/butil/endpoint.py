"""EndPoint — where a peer lives.

Capability parity with butil::EndPoint (/root/reference/src/butil/endpoint.cpp)
extended for TPU pods: an endpoint is either

- a network address ``ip:port`` (IPv4/IPv6/hostname) or unix socket path, or
- a *device coordinate* on an ICI mesh: ``ici://<mesh_name>/<index>`` —
  the TPU-native analogue of ip:port for peers reachable over the
  interconnect rather than a NIC.

Value type: hashable, comparable, parseable/printable.
"""

from __future__ import annotations

import re
import socket
from dataclasses import dataclass
from typing import Optional, Tuple

_ICI_RE = re.compile(r"^ici://([A-Za-z0-9_\-\.]+)/(\d+)$")
_UDS_PREFIX = "unix:"


@dataclass(frozen=True, order=True)
class EndPoint:
    host: str = ""
    port: int = 0
    # device coordinate fields (exclusive with host/port)
    mesh: str = ""
    device_index: int = -1

    @property
    def is_device(self) -> bool:
        return self.device_index >= 0

    @property
    def is_unix(self) -> bool:
        return self.host.startswith(_UDS_PREFIX)

    def __str__(self) -> str:
        if self.is_device:
            return f"ici://{self.mesh}/{self.device_index}"
        if self.is_unix:
            return self.host
        if ":" in self.host:  # ipv6 literal
            return f"[{self.host}]:{self.port}"
        return f"{self.host}:{self.port}"

    def to_sockaddr(self) -> Tuple[str, int]:
        if self.is_device:
            raise ValueError(f"{self} is a device endpoint, not a sockaddr")
        return (self.host, self.port)


def parse_endpoint(text: str, default_port: int = 0) -> EndPoint:
    """Parse ``host:port``, ``[v6]:port``, ``unix:/path``, ``ici://mesh/idx``,
    or bare host (uses default_port)."""
    text = text.strip()
    m = _ICI_RE.match(text)
    if m:
        return EndPoint(mesh=m.group(1), device_index=int(m.group(2)))
    if text.startswith(_UDS_PREFIX):
        return EndPoint(host=text, port=0)
    if text.startswith("["):  # [ipv6]:port
        close = text.index("]")
        host = text[1:close]
        rest = text[close + 1 :]
        port = int(rest[1:]) if rest.startswith(":") else default_port
        return EndPoint(host=host, port=port)
    if text.count(":") == 1:
        host, port_s = text.split(":")
        return EndPoint(host=host, port=int(port_s))
    if text.count(":") > 1:  # bare ipv6
        return EndPoint(host=text, port=default_port)
    if not text:
        raise ValueError("empty endpoint")
    return EndPoint(host=text, port=default_port)


def device_endpoint(mesh: str, index: int) -> EndPoint:
    return EndPoint(mesh=mesh, device_index=index)


def hostname_to_ip(hostname: str) -> str:
    """Resolve a hostname to its first IP (≈ butil::hostname2ip)."""
    return socket.gethostbyname(hostname)


def my_hostname() -> str:
    return socket.gethostname()
