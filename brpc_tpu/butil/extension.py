"""Extension<T> — the universal name→plugin registry
(≈ /root/reference/src/brpc/extension.h:38-53): case-insensitive names,
process-global per category, used by naming services, load balancers and
concurrency limiters so user plugins register alongside builtins."""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")


class Extension(Generic[T]):
    def __init__(self, category: str):
        self.category = category
        self._lock = threading.Lock()
        self._map: Dict[str, T] = {}

    def register(self, name: str, instance: T,
                 allow_override: bool = False) -> None:
        key = name.lower()
        with self._lock:
            if key in self._map and not allow_override:
                raise ValueError(
                    f"{self.category} extension {name!r} already registered")
            self._map[key] = instance

    def find(self, name: str) -> Optional[T]:
        return self._map.get(name.lower())

    def list(self) -> List[str]:
        with self._lock:
            return sorted(self._map)


_registries: Dict[str, Extension] = {}
_registries_lock = threading.Lock()


def extension(category: str) -> Extension:
    """Shared registry for a category (lazily created)."""
    with _registries_lock:
        reg = _registries.get(category)
        if reg is None:
            reg = _registries[category] = Extension(category)
        return reg
