"""Flag system — process-wide named config with live reload.

≈ gflags + BRPC_VALIDATE_GFLAG (/root/reference/src/brpc/reloadable_flags.h
:37,58 and builtin/flags_service.cpp:107-156): flags declare a default +
help; a flag is *reloadable* iff it registered a validator; the HTTP
portal's /flags page can read all and set reloadable ones; every flag is
also visible to the metrics layer.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class Flag:
    __slots__ = ("name", "value", "default", "help", "validator", "type")

    def __init__(self, name: str, default: Any, help_text: str,
                 validator: Optional[Callable[[Any], bool]]):
        self.name = name
        self.value = default
        self.default = default
        self.help = help_text
        self.validator = validator
        self.type = type(default)

    @property
    def reloadable(self) -> bool:
        return self.validator is not None


_lock = threading.Lock()
_flags: Dict[str, Flag] = {}


def define_flag(name: str, default: Any, help_text: str = "",
                validator: Optional[Callable[[Any], bool]] = None) -> Flag:
    with _lock:
        if name in _flags:
            raise ValueError(f"flag {name!r} already defined")
        f = Flag(name, default, help_text, validator)
        _flags[name] = f
        return f


def get_flag(name: str, default: Any = None) -> Any:
    f = _flags.get(name)
    return f.value if f is not None else default


def set_flag(name: str, value: Any) -> bool:
    """Live-set; only reloadable flags accept writes, and the validator
    must pass (≈ flags_service.cpp:135).  Watchers fire after the value
    lands (live consumers that cache derived state — e.g. the native
    engine's dispatch switch — resync here)."""
    f = _flags.get(name)
    if f is None or not f.reloadable:
        return False
    try:
        if f.type is bool and isinstance(value, str):
            typed = value.lower() in ("1", "true", "yes", "on")
        else:
            typed = f.type(value)
    except (TypeError, ValueError):
        return False
    if not f.validator(typed):
        return False
    f.value = typed
    for fn in tuple(_watchers.get(name, ())):  # snapshot: a concurrent
        # watch_flag() must not mutate the list we iterate
        try:
            fn(typed)
        except Exception:               # a broken watcher must not veto
            from .logging_util import LOG
            LOG.exception("flag watcher for %r raised", name)
    return True


_watchers: dict = {}


def watch_flag(name: str, fn: Callable[[Any], None]) -> None:
    """Call ``fn(new_value)`` after every successful live-set of
    ``name``.  Watchers are process-lifetime (no unwatch)."""
    _watchers.setdefault(name, []).append(fn)


def list_flags() -> List[Flag]:
    with _lock:
        return sorted(_flags.values(), key=lambda f: f.name)


def positive(v) -> bool:
    return v > 0


def non_negative(v) -> bool:
    return v >= 0


def any_value(v) -> bool:
    return True


# core flags mirroring reference defaults (SURVEY.md appendix A); each
# must have a live consumer — a settable flag nothing reads is a lie
define_flag("max_body_size", 64 * 1024 * 1024,
            "largest acceptable frame body", positive)
define_flag("health_check_interval_s", 3.0,
            "failed-socket reconnect period", positive)
