"""Copy audit — the Python half of the data-plane copy counters.

The zero-copy invariant must be *asserted by tests, not claimed by
comments* (ISSUE 6): the C++ engine counts its own payload copies in
``engine.telemetry()['data_plane_copies']``; this module counts the
Python side's.  Every place the Python stack materializes or copies
payload bytes at data-plane scale (``IOBuf._append_copy``, ``fetch`` /
``to_bytes``, shm staging, scatter-gather landing) reports here when
auditing is on.

Off by default and gated by a single module-level bool so the hot path
pays one global load + branch; tests flip it with :func:`audit`.

Stages (fixed vocabulary — tests diff these, no "unknown" bucket):

- ``ingest``       bytes copied INTO pool blocks (``_append_copy``)
- ``materialize``  IOBuf → flat bytes (``fetch``/``to_bytes``/copy_to)
- ``gather``       multi-block scatter-gather joined into one buffer
- ``stage_shm``    the shm lane's one staging memcpy into a ring slot
- ``spill_host``   the KV host tier's one memcpy per spilled page
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

STAGES = ("ingest", "materialize", "gather", "stage_shm", "spill_host")

# copies below this size are bookkeeping (headers, metas, small
# payloads), not data-plane traffic — the audit tracks tensor-scale
# movement only
AUDIT_FLOOR = 64 * 1024

enabled = False          # module-global: one load on the hot path

_lock = threading.Lock()
_counts: Dict[str, int] = {s: 0 for s in STAGES}
_bytes: Dict[str, int] = {s: 0 for s in STAGES}


def record(stage: str, nbytes: int) -> None:
    """Count one payload copy of ``nbytes`` (callers pre-check
    ``enabled`` and the floor — this function trusts them)."""
    with _lock:
        _counts[stage] += 1
        _bytes[stage] += nbytes


def snapshot() -> Tuple[Dict[str, int], Dict[str, int]]:
    with _lock:
        return dict(_counts), dict(_bytes)


def total_copies() -> int:
    with _lock:
        return sum(_counts.values())


def reset() -> None:
    with _lock:
        for s in STAGES:
            _counts[s] = 0
            _bytes[s] = 0


class audit:
    """``with copy_audit.audit() as snap:`` — enables auditing for the
    block; ``snap()`` returns (counts, bytes) accumulated since entry."""

    def __enter__(self):
        global enabled
        reset()
        self._was = enabled
        enabled = True
        return snapshot

    def __exit__(self, *exc):
        global enabled
        enabled = self._was
        return False
