"""butil — base library (L0). See SURVEY.md §2.1 for the parity inventory."""

from .iobuf import (IOBuf, IOPortal, IOBufAppender, IOBufReader, Block,
                    BlockPool, HostBlockPool, DEFAULT_BLOCK_SIZE,
                    default_block_pool)
from .resource_pool import (ResourcePool, ObjectPool, INVALID_ID,
                            id_slot, id_version, make_id)
from .doubly_buffered import DoublyBufferedData
from .endpoint import EndPoint, parse_endpoint, device_endpoint
from .flat_map import CaseIgnoredFlatMap, MRUCache, BoundedQueue
from .fast_rand import fast_rand, fast_rand_less_than, fast_rand_in, fast_rand_double
from .crc32c import crc32c, crc32c_extend, hash_bytes64, fmix64
from .time_utils import monotonic_us, monotonic_ms, gettimeofday_us, Timer
from .status import Status, Errno
from .logging_util import LOG, vlog, log_every_n, log_first_n
