"""Maps used across the stack.

Python dicts are already open-addressing hash maps (the reference built
FlatMap, /root/reference/src/butil/containers/flat_map.h, because std::
unordered_map was slow — that rationale doesn't transfer).  What *does*
transfer is the case-ignored map for HTTP headers and the bounded MRU cache.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Iterator, Optional, Tuple


class CaseIgnoredFlatMap:
    """Case-insensitive string map preserving original key casing
    (≈ case_ignored_flat_map.h; used for HTTP headers)."""

    def __init__(self):
        self._d: dict = {}  # lower_key -> (orig_key, value)

    def __setitem__(self, key: str, value) -> None:
        self._d[key.lower()] = (key, value)

    def __getitem__(self, key: str):
        return self._d[key.lower()][1]

    def get(self, key: str, default=None):
        item = self._d.get(key.lower())
        return item[1] if item is not None else default

    def __delitem__(self, key: str) -> None:
        del self._d[key.lower()]

    def __contains__(self, key: str) -> bool:
        return key.lower() in self._d

    def __len__(self) -> int:
        return len(self._d)

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(self._d.values())

    def keys(self):
        return (k for k, _ in self._d.values())

    def clear(self) -> None:
        self._d.clear()


class MRUCache:
    """Bounded most-recently-used cache (≈ butil/containers/mru_cache.h)."""

    def __init__(self, max_size: int):
        self.max_size = max_size
        self._d: OrderedDict = OrderedDict()

    def put(self, key, value) -> None:
        if key in self._d:
            self._d.move_to_end(key)
        self._d[key] = value
        while len(self._d) > self.max_size:
            self._d.popitem(last=False)

    def get(self, key, default=None):
        if key in self._d:
            self._d.move_to_end(key)
            return self._d[key]
        return default

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)


class BoundedQueue:
    """Fixed-capacity FIFO ring (≈ butil/containers/bounded_queue.h)."""

    def __init__(self, capacity: int):
        self._buf = [None] * capacity
        self._cap = capacity
        self._start = 0
        self._count = 0

    def push(self, item) -> bool:
        if self._count >= self._cap:
            return False
        self._buf[(self._start + self._count) % self._cap] = item
        self._count += 1
        return True

    def push_force(self, item) -> None:
        """Push, evicting the oldest if full (elim_push)."""
        if not self.push(item):
            self.pop()
            self.push(item)

    def pop(self):
        if self._count == 0:
            return None
        item = self._buf[self._start]
        self._buf[self._start] = None
        self._start = (self._start + 1) % self._cap
        self._count -= 1
        return item

    def top(self):
        return self._buf[self._start] if self._count else None

    def snapshot(self) -> list:
        """Oldest-first copy of current contents (callers needing cross-
        thread consistency must hold their own lock around push/snapshot)."""
        return [self._buf[(self._start + i) % self._cap]
                for i in range(self._count)]

    def __len__(self) -> int:
        return self._count

    @property
    def full(self) -> bool:
        return self._count >= self._cap
