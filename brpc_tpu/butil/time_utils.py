"""Time helpers (≈ /root/reference/src/butil/time.h).

``cpuwide_time_us`` in the reference is rdtsc-based; here the monotonic
clock is the cheapest precise source Python exposes.
"""

from __future__ import annotations

import time


def monotonic_us() -> int:
    return time.monotonic_ns() // 1000


def monotonic_ms() -> int:
    return time.monotonic_ns() // 1_000_000


def gettimeofday_us() -> int:
    return time.time_ns() // 1000


cpuwide_time_us = monotonic_us


class Timer:
    """Stopwatch (≈ butil::Timer)."""

    def __init__(self, start: bool = False):
        self._start_ns = 0
        self._stop_ns = 0
        if start:
            self.start()

    def start(self) -> None:
        self._start_ns = time.monotonic_ns()
        self._stop_ns = self._start_ns

    def stop(self) -> None:
        self._stop_ns = time.monotonic_ns()

    def n_elapsed(self) -> int:
        return self._stop_ns - self._start_ns

    def u_elapsed(self) -> int:
        return self.n_elapsed() // 1000

    def m_elapsed(self) -> int:
        return self.n_elapsed() // 1_000_000

    def s_elapsed(self) -> float:
        return self.n_elapsed() / 1e9
