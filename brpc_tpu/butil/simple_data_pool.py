"""SimpleDataPool — recycled per-request user data.

≈ /root/reference/src/brpc/simple_data_pool.h: servers hand each request
a reusable "session-local data" object created by a user factory;
returning it to the pool skips re-construction on the next request.
Wired to ``ServerOptions.session_local_data_factory`` +
``ServerController.session_local_data()``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional


class SimpleDataPool:
    def __init__(self, factory: Callable[[], Any],
                 destroy: Optional[Callable[[Any], None]] = None,
                 max_cached: int = 128):
        self._factory = factory
        self._destroy = destroy
        self._max = max_cached
        self._lock = threading.Lock()
        self._free: List[Any] = []
        self.created = 0      # stats (≈ Stat in the reference)
        self.borrowed = 0

    def borrow(self) -> Any:
        with self._lock:
            self.borrowed += 1
            if self._free:
                return self._free.pop()
            self.created += 1
        return self._factory()

    def give_back(self, obj: Any) -> None:
        if obj is None:
            return
        with self._lock:
            self.borrowed -= 1
            if len(self._free) < self._max:
                self._free.append(obj)
                return
        if self._destroy is not None:
            self._destroy(obj)

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)
