"""Logging facade (≈ /root/reference/src/butil/logging.cc): stream-style
levels, LOG_EVERY_N / LOG_FIRST_N rate limiting, pluggable sink, VLOG with
per-module verbosity — mapped onto the stdlib logging machinery rather than
re-inventing handlers.
"""

from __future__ import annotations

import logging
import sys
import threading
from typing import Callable, Dict, Optional

_logger = logging.getLogger("brpc_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(
        logging.Formatter("%(levelname).1s%(asctime)s %(threadName)s %(filename)s:%(lineno)d] %(message)s",
                          datefmt="%m%d %H:%M:%S")
    )
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False

LOG = _logger  # LOG.info / LOG.warning / LOG.error / LOG.fatal≈critical

_counters: Dict[str, int] = {}
_counters_lock = threading.Lock()
_vlog_level = 0


def set_min_log_level(level: int) -> None:
    _logger.setLevel(level)


def set_vlog_level(level: int) -> None:
    global _vlog_level
    _vlog_level = level


def vlog_level() -> int:
    return _vlog_level


def vlog(verbosity: int, msg: str, *args) -> None:
    if verbosity <= _vlog_level:
        _logger.info(msg, *args, stacklevel=2)


def log_every_n(key: str, n: int, level: int, msg: str, *args) -> None:
    with _counters_lock:
        c = _counters.get(key, 0)
        _counters[key] = c + 1
    if c % n == 0:
        _logger.log(level, msg, *args, stacklevel=2)


def log_first_n(key: str, n: int, level: int, msg: str, *args) -> None:
    with _counters_lock:
        c = _counters.get(key, 0)
        if c >= n:
            return
        _counters[key] = c + 1
    _logger.log(level, msg, *args, stacklevel=2)


def add_log_sink(handler: logging.Handler) -> None:
    """Pluggable LogSink (≈ logging::SetLogSink)."""
    _logger.addHandler(handler)


def remove_log_sink(handler: logging.Handler) -> None:
    _logger.removeHandler(handler)
