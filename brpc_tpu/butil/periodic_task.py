"""PeriodicTask — generic repeating work on the shared timer thread.

≈ /root/reference/src/brpc/periodic_task.h: subclass-or-callback runs
every ``interval_s`` until stopped; the callback's return value can
retarget the next interval (return a number) or stop the task (return
False).  Used by health check / naming refresh style maintenance — now
as a public facility.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Union

from ..fiber.timer_thread import global_timer_thread


class PeriodicTask:
    def __init__(self, interval_s: float, fn: Callable[[], object],
                 run_immediately: bool = False):
        self._interval_s = float(interval_s)
        self._fn = fn
        self._lock = threading.Lock()
        self._timer_id = 0
        self._stopped = False
        self.run_count = 0
        if run_immediately:
            self._tick()
        else:
            self._schedule(self._interval_s)

    def _schedule(self, delay_s: float) -> None:
        with self._lock:
            if self._stopped:
                return
            self._timer_id = global_timer_thread().schedule(
                self._tick, delay_s, None)

    def _tick(self) -> None:
        if self._stopped:
            return
        self.run_count += 1
        try:
            ret: Union[bool, float, None] = self._fn()
        except Exception:
            from .logging_util import LOG
            LOG.exception("periodic task raised")
            ret = None
        if ret is False:
            self._stopped = True
            return
        delay = float(ret) if isinstance(ret, (int, float)) \
            and not isinstance(ret, bool) and ret > 0 else self._interval_s
        self._schedule(delay)

    def stop(self) -> None:
        """Idempotent; a tick in flight finishes but does not reschedule."""
        with self._lock:
            self._stopped = True
            if self._timer_id:
                global_timer_thread().unschedule(self._timer_id)
                self._timer_id = 0
