"""Per-thread xorshift PRNG (≈ /root/reference/src/butil/fast_rand.cpp).

Used by load-balancer randomization and backoff jitter; avoids the global
lock inside ``random`` module's shared Random instance.
"""

from __future__ import annotations

import os
import threading

_MASK64 = (1 << 64) - 1


class _State(threading.local):
    def __init__(self):
        seed = int.from_bytes(os.urandom(8), "little") | 1
        self.s = seed


_state = _State()


def fast_rand() -> int:
    """Uniform 64-bit value (xorshift64*)."""
    x = _state.s
    x ^= (x >> 12)
    x ^= (x << 25) & _MASK64
    x ^= (x >> 27)
    _state.s = x
    return (x * 0x2545F4914F6CDD1D) & _MASK64


def fast_rand_less_than(n: int) -> int:
    """Uniform in [0, n)."""
    if n <= 0:
        return 0
    return fast_rand() % n


def fast_rand_in(lo: int, hi: int) -> int:
    """Uniform in [lo, hi] inclusive."""
    if hi < lo:
        lo, hi = hi, lo
    return lo + fast_rand_less_than(hi - lo + 1)


def fast_rand_double() -> float:
    return (fast_rand() >> 11) * (1.0 / (1 << 53))
