"""Cross-cutting utilities with no reference counterpart.

The reference is a stateless RPC framework (SURVEY §5.4: "checkpoint /
resume: none"); a TPU training framework is not — model/optimizer
state must survive preemption.  These modules are fresh designs.
"""

from .checkpoint import TrainCheckpointer, abstract_like

__all__ = ["TrainCheckpointer", "abstract_like"]
