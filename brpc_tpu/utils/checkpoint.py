"""Checkpoint / resume for sharded training state.

Fresh design — the reference has no counterpart (SURVEY §5.4: it is a
stateless RPC framework; its closest analogues are rpc_dump's sampled
request capture, which ``tools/rpc_dump.py`` covers, and bvar's
dump-to-file, covered by ``bvar.dump_exposed``).  A TPU training
framework additionally needs model/optimizer state to survive host
preemption, with shardings restored in place:

- orbax-backed: each host writes its own shards (multi-host safe), any
  pytree of jax arrays works (params, optimizer moments, step counters);
- **sharding-preserving resume**: restoring against an abstract target
  (``jax.eval_shape`` + ``NamedSharding``) lands shards directly on the
  right devices — no host-memory spike, no reshard after load;
- retention: ``max_to_keep`` prunes old steps, ``latest_step()`` +
  ``restore()`` give crash-resume semantics (resume from the newest
  complete checkpoint; partial writes are never visible because orbax
  commits atomically via a rename).
"""

from __future__ import annotations

import os
from typing import Any, Optional


class TrainCheckpointer:
    """Save/restore a training-state pytree with crash-resume semantics.

    >>> ckpt = TrainCheckpointer("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(step, {"params": params, "opt": opt_state})
    >>> state = ckpt.restore(like=abstract_state)   # newest step
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    # -- writing -----------------------------------------------------------

    def save(self, step: int, state: Any, wait: bool = True) -> bool:
        """Persist ``state`` (any pytree of jax/np arrays) as ``step``.
        ``wait=False`` leaves the write in flight (async checkpointing);
        call :meth:`wait` (or the next save) before relying on it."""
        ok = self._mgr.save(int(step),
                            args=self._ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()
        return bool(ok)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    # -- reading -----------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        s = self._mgr.latest_step()
        return int(s) if s is not None else None

    def all_steps(self):
        return sorted(int(s) for s in self._mgr.all_steps())

    def restore(self, like: Any = None, step: Optional[int] = None) -> Any:
        """Restore ``step`` (default: newest).  ``like`` is an abstract
        target — a pytree of ``jax.ShapeDtypeStruct`` (e.g. from
        :func:`abstract_like`) whose ``sharding`` fields place every
        shard directly on its device.  ``like=None`` restores without a
        target: device-resident arrays with orbax-inferred placement —
        only safe when the restoring topology matches the saving one
        (orbax warns on this path); always pass ``like`` to resume."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self._dir}")
        args = (self._ocp.args.StandardRestore(like)
                if like is not None else None)
        return self._mgr.restore(int(step), args=args)

    def close(self) -> None:
        self._mgr.close()


def abstract_like(state: Any) -> Any:
    """Abstract target mirroring ``state``'s shapes/dtypes/shardings —
    pass to :meth:`TrainCheckpointer.restore` to resume sharded."""
    import jax

    def one(x):
        if not hasattr(x, "shape"):
            return x                 # python scalar leaf (step counters)
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree_util.tree_map(one, state)
