"""Streaming RPC — ordered message streams with credit flow control.

≈ /root/reference/src/brpc/stream.h:90,97,107 + policy/
streaming_rpc_protocol.cpp: a stream is established over a normal RPC
(client sends its stream id in the request meta, server answers with its
own in the response meta), then both sides exchange stream frames on the
SAME connection. Flow control is a credit window: the writer blocks once
``produced >= remote_consumed + window`` and resumes when the consumer's
feedback frames advance ``remote_consumed``
(/root/reference/src/brpc/stream.cpp:277,307-337). Messages are
delivered to the handler in order through a per-stream ExecutionQueue,
batched like the reference's on_received_messages.

Wire format (same port, detected like every protocol):

    [ "TSTR" ][ u8 flags ][ u64 dest_stream_id ][ u32 len ][ payload ]
"""

from __future__ import annotations

import itertools
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional

from .butil.iobuf import IOBuf
from .butil.logging_util import LOG
from .butil.status import Errno
from .fiber.execution_queue import ExecutionQueue
from .transport.socket import Socket

# wire constants live with the parser — one definition for both sides
from .protocol.streaming import (F_CLOSE, F_DATA, F_FEEDBACK, F_RST,
                                 MAGIC)

DEFAULT_WINDOW = 2 * 1024 * 1024
_CLOSE_SENTINEL = object()     # ordered close marker in the deliver queue


class StreamOptions:
    __slots__ = ("max_buf_size", "on_received", "on_closed",
                 "write_timeout_s")

    def __init__(self,
                 on_received: Optional[Callable] = None,
                 on_closed: Optional[Callable] = None,
                 max_buf_size: int = DEFAULT_WINDOW,
                 write_timeout_s: float = 30.0):
        # (stream, [msg, ...]); small messages arrive as bytes, large
        # (>=8KB) ones as zero-copy IOBuf views — both support len()
        # and bytes(), like the reference's butil::IOBuf* batches
        self.on_received = on_received
        self.on_closed = on_closed          # (stream)
        self.max_buf_size = max_buf_size
        self.write_timeout_s = write_timeout_s


_streams_lock = threading.Lock()
_streams: Dict[int, "Stream"] = {}
# ids start at a random 48-bit offset so they are not enumerable from a
# fresh connection (the reference's StreamIds are versioned SocketIds and
# equally non-guessable); forged frames are additionally rejected by the
# socket-binding check in protocol/streaming._dispatch.
_next_id = itertools.count(int.from_bytes(os.urandom(6), "little") | 1)


def _register(stream: "Stream") -> int:
    sid = next(_next_id)
    with _streams_lock:
        _streams[sid] = stream
    return sid


def find_stream(stream_id: int) -> Optional["Stream"]:
    return _streams.get(stream_id)


class Stream:
    def __init__(self, options: Optional[StreamOptions] = None):
        self.options = options or StreamOptions()
        self.id = _register(self)
        self.socket_id = 0
        self.peer_stream_id = 0
        # named close reason: set locally by close(reason=...) or from
        # the peer's F_CLOSE payload (wire-compatible — pre-reason
        # receivers ignored the payload); surfaced through on_closed
        self.close_reason: Optional[str] = None
        # kind-5 native write lane: the engine that owns this stream's
        # write-side credit window (server/stream_slim binds it after
        # stream_register) — None means the Python credit path below
        self._native_tx = None
        # the Server that accepted this stream (stream_accept tags it):
        # drain_server_streams closes a draining server's streams with
        # a named reason instead of cutting them at force-close
        self._server = None
        self._established = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        # writer-side credit window = the PEER's advertised receive
        # buffer (set at bind; own buf size is only a pre-bind fallback)
        # RLock: _send_frame failure inside write() re-enters via
        # _close_local's notify
        self._cond = threading.Condition(threading.RLock())
        self._write_window = self.options.max_buf_size
        self._produced = 0
        self._remote_consumed = 0
        # receiver-side accounting: _received counts arrival, _consumed
        # counts DELIVERY — acks reflect consumption so a slow handler
        # backpressures the writer instead of growing the queue
        self._received = 0
        self._consumed = 0
        self._acked = 0
        self._deliver = ExecutionQueue(self._deliver_batch)

    # -- establishment -----------------------------------------------------

    def _bind(self, socket_id: int, peer_stream_id: int,
              peer_window: int = 0) -> None:
        self.socket_id = socket_id
        self.peer_stream_id = peer_stream_id
        if peer_window > 0:
            self._write_window = peer_window
        sock = Socket.address(socket_id)
        if sock is not None and not sock.failed:
            with sock._stream_lock:
                sock.stream_map[self.id] = self
            if sock.failed:
                # raced set_failed's sweep: self-remove and treat as dead
                with sock._stream_lock:
                    sock.stream_map.pop(self.id, None)
                sock = None
        elif sock is not None:
            sock = None
        self._established.set()
        if sock is None:
            self._on_conn_broken()

    def wait_established(self, timeout: float = 10.0) -> bool:
        return self._established.wait(timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- write side --------------------------------------------------------

    def write(self, data) -> int:
        """Ordered write; blocks while the peer's window is full
        (≈ StreamWrite returning EAGAIN→wait, stream.cpp:277).
        IOBuf payloads ride zero-copy (block refs shared into the
        frame, never flattened).  Streams adopted onto the engine's
        kind-5 lane route through the C++ credit window instead
        (chunk framed natively, backpressure = credit exhaustion)."""
        if isinstance(data, str):
            data = data.encode()
        if not self._established.wait(self.options.write_timeout_s):
            return int(Errno.EINTERNAL)
        if self._closed:
            return int(Errno.EEOF)
        engine = self._native_tx
        if engine is not None:
            if isinstance(data, IOBuf):
                data = data.to_bytes()
            st = engine.stream_write(
                self.id, data,
                int(self.options.write_timeout_s * 1000))
            if st == 0:
                return 0
            if st == -1:
                return int(Errno.EOVERCROWDED)   # credit exhaustion
            # closed / connection gone
            self._on_conn_broken()
            return int(Errno.EEOF)
        with self._cond:
            # admit while ANY credit remains (stream.cpp:277) — requiring
            # room for the whole message would deadlock writes larger
            # than the window
            ok = self._cond.wait_for(
                lambda: self._closed or
                (self._produced - self._remote_consumed)
                < self._write_window,
                timeout=self.options.write_timeout_s)
            if not ok:
                return int(Errno.EOVERCROWDED)   # window stayed full
            if self._closed:
                return int(Errno.EEOF)
            self._produced += len(data)
            # send while still holding _cond: two writers woken together
            # must hit the socket in credit-reservation order
            return self._send_frame(F_DATA, data)

    def _send_frame(self, flags: int, payload: bytes = b"") -> int:
        sock = Socket.address(self.socket_id)
        if sock is None or sock.failed:
            self._on_conn_broken()
            return int(Errno.EFAILEDSOCKET)
        frame = IOBuf(MAGIC + struct.pack("<BQI", flags,
                                          self.peer_stream_id,
                                          len(payload)))
        if isinstance(payload, IOBuf):
            frame.append_iobuf(payload)      # share blocks, no flatten
        elif payload:
            frame.append(payload)            # zero-copy for large bytes
        return sock.write(frame)

    # -- frame ingestion (called by the protocol layer) -------------------

    def on_frame(self, flags: int, payload: bytes) -> None:
        if flags == F_DATA:
            self._received += len(payload)
            self._deliver.execute(payload)
        elif flags == F_FEEDBACK:
            (consumed,) = struct.unpack("<Q", payload[:8])
            with self._cond:
                if consumed > self._remote_consumed:
                    self._remote_consumed = consumed
                    self._cond.notify_all()
        elif flags == F_RST:
            self._close_local(notify_peer=False)
        elif flags == F_CLOSE:
            # ordered close: runs through the deliver queue so data cut
            # before the FIN is handed to on_received first.  A non-
            # empty payload is the peer's NAMED close reason (drain
            # lame-duck, decode "finished", ...)
            if payload and self.close_reason is None:
                try:
                    self.close_reason = bytes(payload).decode(
                        "utf-8", "replace")
                except Exception:
                    self.close_reason = "peer_close"
            self._deliver.execute(_CLOSE_SENTINEL)

    def _deliver_batch(self, it) -> None:
        msgs = list(it)
        close_after = _CLOSE_SENTINEL in msgs
        msgs = [m for m in msgs if m is not _CLOSE_SENTINEL]
        if msgs:
            # consumption = dequeued for processing: ack BEFORE the
            # handler (the reference advances remote_consumed on pop,
            # stream.cpp:307 — an on_received that writes back and blocks
            # on peer credit must not stall its own acks). on_received
            # should still not block forever; offload long work.
            self._consumed += sum(len(m) for m in msgs)
            if (self._consumed - self._acked
                    >= self.options.max_buf_size // 2) \
                    and not self._closed:
                self._acked = self._consumed
                self._send_frame(F_FEEDBACK,
                                 struct.pack("<Q", self._consumed))
            if self.options.on_received is not None:
                try:
                    self.options.on_received(self, msgs)
                except Exception:
                    LOG.exception("stream on_received raised")
        if close_after:
            self._close_local(notify_peer=False)

    # -- teardown ----------------------------------------------------------

    def close(self, reason: Optional[str] = None) -> None:
        """Graceful: FIN to peer (carrying the NAMED reason when
        given), then local close."""
        self._close_local(notify_peer=True, reason=reason)

    def drain_close(self, reason: str, settle_timeout_s: float) -> None:
        """Operability-plane close: give the CURRENT chunk window a
        short bounded settle before the FIN, so a draining server ends
        streams after the in-flight chunks instead of cutting a
        producer mid-window.  The FIN itself is ordered AFTER every
        already-queued data frame on the connection, so delivery never
        truncates regardless; this wait only lets an in-progress
        producer finish.  It is deliberately capped well below the
        drain grace — receivers ack at half-window granularity, so
        ``produced == consumed`` may never hold and an uncapped wait
        would burn the whole grace on the first stream (starving the
        in-flight RPC settle that follows).  Native-lane streams
        settle through the engine's write queue (their ledger lives in
        C++)."""
        if self._closed:
            return
        if self._native_tx is None:
            cap = min(max(settle_timeout_s, 0.0), 0.25)
            with self._cond:
                self._cond.wait_for(
                    lambda: self._closed
                    or self._produced <= self._remote_consumed,
                    timeout=cap)
        self.close(reason=reason)

    def _close_local(self, notify_peer: bool,
                     reason: Optional[str] = None) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        if reason is not None and self.close_reason is None:
            self.close_reason = reason
        engine = self._native_tx
        if engine is not None:
            # drop off the kind-5 lane FIRST: a racing producer fails
            # fast instead of writing after the FIN
            self._native_tx = None
            try:
                engine.stream_unregister(self.id)
            except Exception:
                pass
        if notify_peer and self.peer_stream_id:
            self._send_frame(F_CLOSE,
                            reason.encode() if reason else b"")
        with self._cond:
            self._cond.notify_all()
        sock = Socket.address(self.socket_id)
        if sock is not None:
            with sock._stream_lock:
                sock.stream_map.pop(self.id, None)
        with _streams_lock:
            _streams.pop(self.id, None)
        self._deliver.stop()
        if self.options.on_closed is not None:
            try:
                self.options.on_closed(self)
            except Exception:
                LOG.exception("stream on_closed raised")

    def _on_conn_broken(self) -> None:
        self._close_local(notify_peer=False)


# -- establishment helpers (≈ StreamCreate / StreamAccept) ----------------

def stream_create(cntl, options: Optional[StreamOptions] = None) -> Stream:
    """Client side, BEFORE issuing the RPC: attaches the stream to the
    controller; the response binds it (≈ StreamCreate, stream.h:90)."""
    s = Stream(options)
    cntl._stream_to_create = s
    return s


def stream_accept(cntl, options: Optional[StreamOptions] = None) \
        -> Optional[Stream]:
    """Server side, inside the method: accept the request's stream
    (≈ StreamAccept, stream.h:97)."""
    peer_id = getattr(cntl, "_remote_stream_id", 0)
    if not peer_id:
        return None
    s = Stream(options)
    s._server = getattr(cntl, "server", None)   # drain enumeration
    s._bind(cntl.socket_id, peer_id,
            peer_window=cntl.request_meta.stream_window)
    cntl._accepted_stream_id = s.id
    cntl._accepted_stream_window = s.options.max_buf_size
    return s


def server_streams(server) -> List[Stream]:
    """Live streams accepted by ``server`` (tagged at stream_accept)."""
    with _streams_lock:
        return [s for s in _streams.values() if s._server is server]


def drain_server_streams(server, deadline_mono: float,
                         reason: str = "lame_duck") -> int:
    """Operability plane: gracefully end every in-flight stream a
    draining server accepted — each gets the bounded current-window
    settle then a FIN carrying the NAMED reason, instead of dying at
    the drain's force-close.  Bounded by ``deadline_mono`` (the drain
    grace); returns how many streams were closed."""
    n = 0
    for s in server_streams(server):
        left = deadline_mono - time.monotonic()
        s.drain_close(reason, settle_timeout_s=max(left, 0.0))
        n += 1
    return n
