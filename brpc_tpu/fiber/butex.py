"""Butex — futex-shaped blocking primitive
(≈ /root/reference/src/bthread/butex.cpp:283): wait iff the value still
equals the expected value; wakers bump the value and wake waiters.  All
higher-level blocking (call join, stream windows, countdown) builds on it,
mirroring the reference's layering.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..butil import sanitizers as _san
from .runtime import blocking


class Butex:
    """Futex semantics: ``wait`` sleeps only if the value still equals
    ``expected`` at entry, and then ANY ``wake`` releases it regardless of
    the value (a generation counter prevents re-blocking on a stale
    predicate — the lost-wakeup guard the reference gets from the kernel
    futex). Spurious wakeups are allowed, as with real futexes: callers
    re-check their own condition in a loop."""

    __slots__ = ("_value", "_gen", "_cond")

    def __init__(self, value: int = 0):
        self._value = value
        self._gen = 0
        self._cond = threading.Condition()

    @property
    def value(self) -> int:
        return self._value

    def set_value(self, v: int) -> None:
        """Plain store, no wake — exactly a memory write to the futex word."""
        with self._cond:
            self._value = v

    def wait(self, expected: int, timeout: Optional[float] = None) -> bool:
        """Returns True if woken (or the value had already changed),
        False on timeout."""
        with self._cond:
            if self._value != expected:
                return True
            g = self._gen
            with blocking():
                from .. import profiling
                waitfn = lambda: self._cond.wait_for(  # noqa: E731
                    lambda: self._gen != g or self._value != expected,
                    timeout)
                if profiling.contention_active():
                    return profiling.timed_wait("butex", waitfn)
                if _san.watchdog_enabled():
                    with _san.watched_wait("butex"):
                        return waitfn()
                return waitfn()

    def wake(self, n: int = 1) -> None:
        with self._cond:
            self._gen += 1
            self._cond.notify(n)

    def wake_all(self) -> None:
        with self._cond:
            self._gen += 1
            self._cond.notify_all()

    def add_and_wake(self, delta: int = 1, all: bool = True) -> int:
        """Atomically bump the value and wake waiters — the common
        signal pattern."""
        with self._cond:
            self._value += delta
            self._gen += 1
            if all:
                self._cond.notify_all()
            else:
                self._cond.notify(1)
            return self._value


class CountdownEvent:
    """≈ bthread::CountdownEvent — join N things."""

    def __init__(self, count: int = 1):
        self._butex = Butex(count)

    def signal(self, n: int = 1) -> None:
        self._butex.add_and_wake(-n)

    def add_count(self, n: int = 1) -> None:
        self._butex.add_and_wake(n, all=False)

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._butex._cond:
            with blocking():
                from .. import profiling
                waitfn = lambda: self._butex._cond.wait_for(  # noqa: E731
                    lambda: self._butex._value <= 0, timeout)
                if profiling.contention_active():
                    return profiling.timed_wait("countdown", waitfn)
                if _san.watchdog_enabled():
                    with _san.watched_wait("countdown"):
                        return waitfn()
                return waitfn()

    @property
    def count(self) -> int:
        return self._butex.value
