"""fiber — the task runtime (L1). SURVEY.md §2.2 inventory."""

from .runtime import (TaskRuntime, TaskHandle, spawn, global_runtime,
                      set_concurrency, blocking, DEFAULT_CONCURRENCY)
from .butex import Butex, CountdownEvent
from .versioned_id import IdPool, global_id_pool, INVALID_CALL_ID
from .execution_queue import ExecutionQueue, TaskIterator
from .timer_thread import TimerThread, global_timer_thread
