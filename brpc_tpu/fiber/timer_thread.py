"""TimerThread — one dedicated thread, nearest-deadline sleep
(≈ /root/reference/src/bthread/timer_thread.h:63): backs RPC deadlines,
backup-request triggers, health-check schedules.

Fresh design: a single heap + Condition (the reference's hashed buckets
reduce multi-core contention that the GIL already serializes away).
``schedule`` returns a TimerId; ``unschedule`` is O(1) (lazy deletion).
Callbacks run on the task runtime, never on the timer thread itself, so a
slow callback cannot delay other timers.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, Optional

from ..butil.logging_util import LOG
from .runtime import TaskRuntime, global_runtime


class TimerThread:
    def __init__(self, runtime: Optional[TaskRuntime] = None,
                 name: str = "timer"):
        self._runtime = runtime or global_runtime()
        self._heap = []                      # (abstime, seq)
        self._entries: Dict[int, tuple] = {} # seq -> (fn, args)
        self._seq = itertools.count(1)
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._name = name
        self.scheduled_count = 0
        self.triggered_count = 0
        self.cancelled_count = 0

    def _ensure_thread(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name=self._name, daemon=True)
            self._thread.start()

    def schedule(self, fn: Callable, delay_s: float = 0.0,
                 abstime: Optional[float] = None, *args) -> int:
        """Run fn(*args) at abstime (monotonic) or after delay_s.
        Returns a TimerId."""
        when = abstime if abstime is not None else time.monotonic() + delay_s
        with self._cond:
            seq = next(self._seq)
            self._entries[seq] = (fn, args)
            heapq.heappush(self._heap, (when, seq))
            self.scheduled_count += 1
            self._ensure_thread()
            # wake the timer thread if this became the nearest deadline
            if self._heap[0][1] == seq:
                self._cond.notify()
        return seq

    def unschedule(self, timer_id: int) -> bool:
        """Cancel; returns True if the timer had not fired yet."""
        with self._cond:
            if timer_id in self._entries:
                del self._entries[timer_id]   # lazy: heap entry skipped later
                self.cancelled_count += 1
                return True
            return False

    def _run(self) -> None:
        while True:
            with self._cond:
                now = time.monotonic()
                fire = []
                while self._heap and self._heap[0][0] <= now:
                    when, seq = heapq.heappop(self._heap)
                    entry = self._entries.pop(seq, None)
                    if entry is not None:
                        fire.append(entry)
                if self._stop:
                    return
                if not fire:
                    if self._heap:
                        self._cond.wait(self._heap[0][0] - now)
                    else:
                        self._cond.wait()
            for fn, args in fire:
                self.triggered_count += 1
                try:
                    self._runtime.spawn(fn, *args, urgent=True,
                                        name="timer_cb")
                except Exception:
                    # a dead runtime must not kill the timer thread — every
                    # future RPC deadline would silently never fire
                    LOG.exception("timer callback spawn failed")

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()


_global_timer: Optional[TimerThread] = None
_global_timer_lock = threading.Lock()


def global_timer_thread() -> TimerThread:
    global _global_timer
    if _global_timer is None:
        with _global_timer_lock:
            if _global_timer is None:
                _global_timer = TimerThread()
    return _global_timer
