"""Task runtime — the concurrency substrate of the framework.

Capability parity with bthread's M:N scheduler
(/root/reference/src/bthread/task_group.h, task_control.h): spawn cheap
tasks, steal-balanced workers, parking when idle, urgent vs background
start.  Design differences, deliberate:

- CPython's GIL makes user-space context switching pointless for *compute*;
  what the RPC stack needs from the runtime is (a) cheap task handoff,
  (b) workers that never sit on a blocked task when runnable work exists,
  (c) bounded thread growth when tasks block on IO/butex — the same
  deadlock-avoidance job as the reference's ``usercode_in_pthread`` backup
  pool (/root/reference/src/brpc/details/usercode_backup_pool.h:30-60).
  So: a dynamic pool with a shared run queue, LIFO slot for urgent starts,
  and on-demand worker growth up to ``max_workers`` when all workers are
  busy/blocked.
- This Python runtime is the control-plane engine; the transport hot
  path (syscalls + framing) is handled by the optional native C++ IO
  engine under ``brpc_tpu/native`` when built, which releases the GIL
  around its epoll/read/write loops.

Wake-up discipline (≈ ParkingLot, parking_lot.h): every COOPERATIVE
path is event-driven — spawn() notifies a parked worker the moment an
item lands (shared queue or a local queue another worker can steal),
and butex/join/socket waits announce themselves via begin_blocking()
so a replacement starts immediately when runnable work would starve.
The only poll in the design is the 50ms starvation monitor, and it
exists for the one case no event can cover: arbitrary user code
blocking a worker WITHOUT telling anyone (third-party sleeps, raw
syscalls) — the same hole the reference plugs with its
usercode_in_pthread backup pool.  The monitor runs only while work is
queued and retires itself when traffic stops.
"""

from __future__ import annotations

import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Deque, List, Optional

from ..butil.logging_util import LOG
from ..bvar.passive_status import PassiveStatus
from ..bvar.reducer import Adder

DEFAULT_CONCURRENCY = 9          # ≈ reference default 8 workers + 1 (bthread.cpp:102)
MAX_WORKERS = 256
IDLE_TIMEOUT_S = 30.0
STARVATION_CHECK_S = 0.05

_tls = threading.local()         # current worker's runtime (for blocking marks)


class TaskHandle:
    """Join-able handle for a spawned task (≈ bthread_t + bthread_join)."""

    __slots__ = ("_done", "_result", "_exc", "fn_name")

    def __init__(self, fn_name: str = ""):
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None
        self.fn_name = fn_name

    def join(self, timeout: Optional[float] = None) -> bool:
        with blocking():
            from .. import profiling
            if profiling.contention_active():
                return profiling.timed_wait(
                    "join", lambda: self._done.wait(timeout))
            return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        with blocking():
            done = self._done.wait(timeout)
        if not done:
            raise TimeoutError(f"task {self.fn_name} not done")
        if self._exc is not None:
            raise self._exc
        return self._result


class TaskRuntime:
    def __init__(self, concurrency: int = DEFAULT_CONCURRENCY,
                 max_workers: int = MAX_WORKERS, name: str = "fiber"):
        self.concurrency = concurrency
        self.max_workers = max_workers
        self.name = name
        self._queue: Deque = deque()          # FIFO background + LIFO urgent
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._workers = 0
        self._idle = 0
        self._blocked = 0        # workers inside cooperative blocking marks
        self._dequeues = 0       # progress counter for the starvation monitor
        self._monitor_running = False
        self._shutdown = False
        self._spawned = Adder()
        self._worker_seq = 0
        # per-worker local queues (≈ bthread's WorkStealingQueue,
        # work_stealing_queue.h): a worker spawning a task pushes it to
        # its OWN queue (LIFO pop keeps the continuation cache-hot);
        # other workers steal FIFO when their own queue and the shared
        # queue are dry.  Guarded by self._lock for list mutations.
        self._local_queues: List = []

    # -- introspection (exposed as bvars by Server) --

    @property
    def worker_count(self) -> int:
        return self._workers

    @property
    def pending_count(self) -> int:
        return len(self._queue) + sum(len(q) for q in self._local_queues)

    def spawn(self, fn: Callable, *args, urgent: bool = False,
              name: str = "") -> TaskHandle:
        """Start a task (≈ bthread_start_urgent/background). ``urgent``
        tasks go to the front of the shared queue; a task spawned FROM a
        worker lands on that worker's local queue (work stealing)."""
        handle = TaskHandle(name or getattr(fn, "__name__", "task"))
        item = (fn, args, handle)
        wsq = getattr(_tls, "wsq", None) \
            if getattr(_tls, "runtime", None) is self else None
        if wsq is not None and not urgent and wsq.push(item):
            self._spawned.update(1)
            with self._lock:
                if self._shutdown:
                    pass          # drain path below still runs the task
                if self._idle > 0:
                    self._not_empty.notify()
                elif self._effective_workers_locked() < self.concurrency:
                    self._add_worker_locked()
                else:
                    self._ensure_monitor_locked()
            return handle
        with self._lock:
            if self._shutdown:
                raise RuntimeError("runtime is shut down")
            if urgent:
                self._queue.appendleft(item)
            else:
                self._queue.append(item)
            self._spawned.update(1)
            if self._idle > 0:
                self._not_empty.notify()
            elif self._effective_workers_locked() < self.concurrency:
                self._add_worker_locked()
            else:
                # all workers busy at target concurrency: let the
                # starvation monitor grow the pool if they're blocked
                self._ensure_monitor_locked()
        return handle

    def _effective_workers_locked(self) -> int:
        """Workers doing (or able to do) CPU work: excludes ones parked in
        cooperative blocking sections."""
        return self._workers - self._blocked

    # -- blocking compensation (≈ usercode_in_pthread deadlock avoidance) --

    def begin_blocking(self) -> None:
        """Called by framework primitives (butex/join/socket waits) before a
        worker blocks: spawns a replacement if runnable work would starve."""
        with self._lock:
            self._blocked += 1
            if ((self._queue or any(self._local_queues))
                    and self._idle == 0
                    and self._workers < self.max_workers
                    and self._effective_workers_locked() < self.concurrency):
                self._add_worker_locked()

    def end_blocking(self) -> None:
        with self._lock:
            self._blocked -= 1

    def _ensure_monitor_locked(self) -> None:
        if not self._monitor_running:
            self._monitor_running = True
            t = threading.Thread(target=self._monitor_loop,
                                 name=f"{self.name}_monitor", daemon=True)
            t.start()

    def _monitor_loop(self) -> None:
        """Detects starvation from *uncooperative* blocking (arbitrary user
        code sleeping/IO-ing on a worker): if the queue is non-empty and no
        dequeue happened across a check interval, add a worker."""
        import time as _time
        idle_rounds = 0
        while True:
            with self._lock:
                last = self._dequeues
            _time.sleep(STARVATION_CHECK_S)
            with self._lock:
                if self._shutdown:
                    self._monitor_running = False
                    return
                if self._queue or any(self._local_queues):
                    idle_rounds = 0
                    if (self._dequeues == last and self._idle == 0
                            and self._workers < self.max_workers):
                        self._add_worker_locked()
                else:
                    idle_rounds += 1
                    if idle_rounds > 100:
                        self._monitor_running = False
                        return

    def _add_worker_locked(self) -> None:
        self._worker_seq += 1
        self._workers += 1
        t = threading.Thread(target=self._worker_loop,
                             name=f"{self.name}_w{self._worker_seq}",
                             daemon=True)
        t.start()

    def _steal_locked(self, my_wsq):
        """One item from the shared queue or another worker's local
        queue; None when everything is dry.  Runs under self._lock."""
        if self._queue:
            return self._queue.popleft()
        for wsq in self._local_queues:
            if wsq is my_wsq:
                continue
            ok, item = wsq.steal()
            if ok:
                return item
        return None

    def _worker_loop(self) -> None:
        from ..butil.work_stealing_queue import WorkStealingQueue
        my_wsq = WorkStealingQueue()
        _tls.runtime = self
        _tls.wsq = my_wsq
        with self._lock:
            self._local_queues.append(my_wsq)
        core = True
        try:
            while True:
                ok, item = my_wsq.pop()       # own continuations first
                if not ok:
                    with self._lock:
                        item = self._steal_locked(my_wsq)
                        while item is None and not self._shutdown:
                            self._idle += 1
                            try:
                                # extra (non-core) workers retire on idle
                                core = self._workers <= self.concurrency
                                signalled = self._not_empty.wait(
                                    None if core else IDLE_TIMEOUT_S)
                            finally:
                                self._idle -= 1
                            item = self._steal_locked(my_wsq)
                            if item is None and not signalled and not core:
                                self._workers -= 1
                                return
                        if item is None:      # shutdown and dry
                            self._workers -= 1
                            return
                        self._dequeues += 1
                else:
                    # GIL-atomic enough for the starvation monitor's
                    # progress check; no global lock on the hot path
                    self._dequeues += 1
                fn, args, handle = item
                try:
                    handle._result = fn(*args)
                except BaseException as e:
                    handle._exc = e
                    LOG.error("task %s raised: %s\n%s", handle.fn_name, e,
                              traceback.format_exc())
                finally:
                    handle._done.set()
        finally:
            # retirement/shutdown: strand no local work — move remnants
            # to the shared queue and wake a peer
            with self._lock:
                try:
                    self._local_queues.remove(my_wsq)
                except ValueError:
                    pass
                moved = False
                while True:
                    ok, item = my_wsq.steal()
                    if not ok:
                        break
                    self._queue.append(item)
                    moved = True
                if moved:
                    self._not_empty.notify()
            _tls.wsq = None

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._shutdown = True
            self._not_empty.notify_all()
        if wait:
            # workers drain the queue then retire; poll until none remain
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                with self._lock:
                    if self._workers == 0 and not self._queue:
                        return
                time.sleep(0.005)


_global_runtime: Optional[TaskRuntime] = None
_global_lock = threading.Lock()


def global_runtime() -> TaskRuntime:
    global _global_runtime
    if _global_runtime is None:
        with _global_lock:
            if _global_runtime is None:
                _global_runtime = TaskRuntime()
    return _global_runtime


def spawn(fn: Callable, *args, urgent: bool = False, name: str = "") -> TaskHandle:
    return global_runtime().spawn(fn, *args, urgent=urgent, name=name)


def set_concurrency(n: int) -> None:
    """≈ bthread_setconcurrency."""
    global_runtime().concurrency = n


class blocking:
    """Context manager marking the current worker as blocked so the
    runtime compensates with another worker.  No-op off worker threads.
    Framework blocking primitives (butex waits, call joins, socket waits)
    use this; user code doing long blocking calls on a fiber should too.
    """

    def __enter__(self):
        rt = getattr(_tls, "runtime", None)
        self._rt = rt
        if rt is not None:
            rt.begin_blocking()
        return self

    def __exit__(self, *exc):
        if self._rt is not None:
            self._rt.end_blocking()
        return False
