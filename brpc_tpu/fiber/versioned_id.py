"""Versioned correlation ids — THE RPC rendezvous mechanism.

Capability parity with bthread_id (/root/reference/src/bthread/id.h:46):
a 64-bit handle protecting an object (the in-flight Call), where

- ``lock(id)`` serializes access from response threads / timers / cancel;
- ``error(id, code)`` delivers asynchronous failures through the
  registered handler, queued if the id is currently locked;
- ranged ids (``create_ranged``, id.h:56) make *retry attempt k* address
  the same call as version ``base+k`` — a stale response from attempt 0
  can still find (and be distinguished by) the call object;
- ``join(id)`` blocks until the call is destroyed;
- destroying bumps the version so stale ids resolve to nothing.

Fresh design: one Condition per slot guards {locked, pending errors,
version}; no global lock on the hot path.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

# id layout: (slot_index << VERSION_BITS) | version
VERSION_BITS = 36
_VERSION_MASK = (1 << VERSION_BITS) - 1

INVALID_CALL_ID = 0

# on_error(call_id, data, error_code, error_text) — called with the id
# LOCKED; the handler must unlock or unlock_and_destroy.
ErrorHandler = Callable[[int, Any, int, str], None]


class _Slot:
    __slots__ = ("cond", "data", "on_error", "base", "range", "locked",
                 "pending", "joiners_wake")

    def __init__(self):
        self.cond = threading.Condition()
        self.data = None
        self.on_error: Optional[ErrorHandler] = None
        self.base = 1          # first valid version
        self.range = 1
        self.locked = False
        self.pending: deque = deque()   # queued (call_id, code, text)


class IdPool:
    def __init__(self):
        self._slots: List[_Slot] = []
        self._free: List[int] = []
        self._alloc_lock = threading.Lock()

    # -- lifecycle --

    def create(self, data: Any = None,
               on_error: Optional[ErrorHandler] = None,
               version_range: int = 1) -> int:
        with self._alloc_lock:
            if self._free:
                idx = self._free.pop()
                slot = self._slots[idx]
            else:
                idx = len(self._slots)
                slot = _Slot()
                self._slots.append(slot)
        with slot.cond:
            slot.data = data
            slot.on_error = on_error or _default_on_error(self)
            slot.range = max(1, version_range)
            slot.locked = False
            slot.pending.clear()
            return (idx << VERSION_BITS) | slot.base

    def create_ranged(self, data: Any, on_error: Optional[ErrorHandler],
                      version_range: int) -> int:
        """Versions [base, base+range) all address this call; callers
        derive sub-ids with ``first_id + k`` for retry attempt k."""
        return self.create(data, on_error, version_range)

    def _resolve(self, call_id: int) -> Tuple[Optional[_Slot], int]:
        idx = call_id >> VERSION_BITS
        version = call_id & _VERSION_MASK
        try:
            slot = self._slots[idx]
        except IndexError:
            return None, 0
        return slot, version

    def _valid_locked(self, slot: _Slot, version: int) -> bool:
        return slot.base <= version < slot.base + slot.range

    def valid(self, call_id: int) -> bool:
        slot, version = self._resolve(call_id)
        if slot is None:
            return False
        with slot.cond:
            return self._valid_locked(slot, version)

    # -- locking protocol --

    def lock(self, call_id: int) -> Tuple[bool, Any]:
        """Blocks until the id lock is held. Returns (ok, data); ok=False
        if the id is stale/destroyed."""
        slot, version = self._resolve(call_id)
        if slot is None:
            return False, None
        with slot.cond:
            while True:
                if not self._valid_locked(slot, version):
                    return False, None
                if not slot.locked:
                    slot.locked = True
                    return True, slot.data
                slot.cond.wait()

    def try_lock(self, call_id: int) -> Tuple[int, Any]:
        """Non-blocking :meth:`lock`: (1, data) = locked, (0, None) =
        currently held by another owner (caller must not wait here),
        (-1, None) = stale/destroyed.  The client lane's demux thread
        uses this so one contended id (a backup-request handler mid-
        connect) can never stall every connection's completions."""
        slot, version = self._resolve(call_id)
        if slot is None:
            return -1, None
        with slot.cond:
            if not self._valid_locked(slot, version):
                return -1, None
            if slot.locked:
                return 0, None
            slot.locked = True
            return 1, slot.data

    def unlock(self, call_id: int) -> None:
        """Release the lock; if errors were queued while locked, run the
        handler for the next one (still holding the logical id lock)."""
        slot, version = self._resolve(call_id)
        if slot is None:
            return
        run: Optional[Tuple[int, int, str]] = None
        with slot.cond:
            # a stale id must not release a lock now owned by the slot's
            # next incarnation (slot indexes are recycled)
            if not slot.locked or not self._valid_locked(slot, version):
                return
            if slot.pending:
                run = slot.pending.popleft()
                # keep slot.locked = True: handler owns the lock now
            else:
                slot.locked = False
                slot.cond.notify_all()
        if run is not None:
            # deliver with the id the error was RAISED for — a ranged
            # id's version is how the handler knows WHICH attempt
            # failed; substituting the unlocker's call_id re-errored
            # version 0 forever (retry chain spun, call never ended)
            qid, code, text = run
            slot.on_error(qid, slot.data, code, text)

    def unlock_and_destroy(self, call_id: int) -> bool:
        slot, version = self._resolve(call_id)
        if slot is None:
            return False
        with slot.cond:
            if not self._valid_locked(slot, version):
                return False             # stale id: never touch lock state
            slot.base += slot.range      # all versions in range die at once
            slot.locked = False
            slot.data = None
            slot.pending.clear()
            slot.cond.notify_all()       # wake joiners & lock waiters
        with self._alloc_lock:
            self._free.append(call_id >> VERSION_BITS)
        return True

    # -- async error delivery --

    def error(self, call_id: int, error_code: int,
              error_text: str = "") -> bool:
        """Deliver an error to the call. If the id is locked, the error is
        queued and delivered on unlock; otherwise the handler runs now,
        holding the id lock (≈ bthread_id_error, id.h:75)."""
        slot, version = self._resolve(call_id)
        if slot is None:
            return False
        with slot.cond:
            if not self._valid_locked(slot, version):
                return False
            if slot.locked:
                slot.pending.append((call_id, error_code, error_text))
                return True
            slot.locked = True
        slot.on_error(call_id, slot.data, error_code, error_text)
        return True

    def join(self, call_id: int, timeout: Optional[float] = None) -> bool:
        """Block until the id is destroyed (≈ bthread_id_join)."""
        slot, version = self._resolve(call_id)
        if slot is None:
            return True
        with slot.cond:
            waitfn = lambda: slot.cond.wait_for(       # noqa: E731
                lambda: not self._valid_locked(slot, version), timeout)
            from ..butil import sanitizers as _san
            if _san.watchdog_enabled():
                # the RPC-join wait: the one users see when a call hangs
                with _san.watched_wait("rpc_join"):
                    return waitfn()
            return waitfn()


def _default_on_error(pool: "IdPool") -> ErrorHandler:
    def handler(call_id: int, data: Any, code: int, text: str) -> None:
        pool.unlock_and_destroy(call_id)
    return handler


_global_pool = IdPool()


def global_id_pool() -> IdPool:
    return _global_pool
