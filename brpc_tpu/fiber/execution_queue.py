"""ExecutionQueue — MPSC queue with an auto-started single consumer
(≈ /root/reference/src/bthread/execution_queue.h:159).

Producers call ``execute(item)`` from any thread; exactly one consumer
task drains batches through the executor callback, then parks itself when
empty (auto-quit).  A high-priority lane jumps the line.  Backs the Socket
write path and load-balancer membership updates, as in the reference.

The executor receives a TaskIterator; iterating consumes items.  If the
queue was stopped, ``iterator.stopped`` is True and remaining items should
be handled as cancelled (mirrors TaskIterator doc, execution_queue.h:78).
"""

from __future__ import annotations

import re
import threading
from collections import deque
from typing import Any, Callable, Deque, Iterator, Optional

from ..butil.sanitizers import DebugLock
from .runtime import TaskRuntime, global_runtime


class TaskIterator:
    def __init__(self, items: Deque, stopped: bool):
        self._items = items
        self.stopped = stopped

    def __iter__(self) -> Iterator[Any]:
        while self._items:
            yield self._items.popleft()

    def __len__(self) -> int:
        return len(self._items)


class ExecutionQueue:
    def __init__(self, executor: Callable[[TaskIterator], None],
                 runtime: Optional[TaskRuntime] = None, name: str = "execq"):
        self._executor = executor
        self._runtime = runtime or global_runtime()
        self._name = name
        # lock-order-instrumented queue lock (butil/sanitizers): under
        # the debug_lock_order flag, ABBA inversions between queue
        # ROLES (instance digits stripped — per-conn queues must not
        # grow the order graph without bound) and other DebugLocks
        # warn before the timing ever deadlocks; flag off = plain Lock
        # pass-through
        self._lock = DebugLock(
            "execq:" + (re.sub(r"[_0-9]+$", "", name) or "execq"))
        self._queue: Deque = deque()
        self._high: Deque = deque()
        self._running = False
        self._stopped = False
        self._drained = threading.Condition(self._lock)

    def execute(self, item: Any, high_priority: bool = False) -> bool:
        """Enqueue; returns False if the queue was stopped."""
        with self._lock:
            if self._stopped:
                return False
            (self._high if high_priority else self._queue).append(item)
            if not self._running:
                self._running = True
                self._runtime.spawn(self._consume, name=self._name)
        return True

    def _consume(self) -> None:
        while True:
            with self._lock:
                if not self._high and not self._queue:
                    self._running = False
                    self._drained.notify_all()
                    return
                batch: Deque = deque()
                while self._high:
                    batch.append(self._high.popleft())
                while self._queue:
                    batch.append(self._queue.popleft())
                stopped = self._stopped
            it = TaskIterator(batch, stopped)
            try:
                self._executor(it)
            except Exception:
                from ..butil.logging_util import LOG
                LOG.exception("execution queue %s executor raised", self._name)
            # loop: re-check for items enqueued while we were executing

    def stop(self) -> None:
        with self._lock:
            self._stopped = True

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait until everything enqueued has been consumed."""
        with self._lock:
            return self._drained.wait_for(
                lambda: not self._running and not self._queue and not self._high,
                timeout)

    @property
    def pending(self) -> int:
        return len(self._queue) + len(self._high)
