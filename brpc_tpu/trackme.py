"""trackme — fleet version phone-home.

≈ /root/reference/src/brpc/details/trackme.cpp: clients ping a central
"trackme" server at a gentle interval reporting their framework
version; the server answers with a severity + message so operators can
flag fleets running buggy/ancient builds.  Server half is the builtin
``/trackme`` page (flag-tunable version gates); client half is
:func:`start_trackme` driven by the ``trackme_server`` flag.
"""

from __future__ import annotations

import json
from typing import Optional

from . import __version__
from .butil.flags import define_flag, get_flag
from .butil.logging_util import LOG
from .butil.periodic_task import PeriodicTask

define_flag("trackme_server", "",
            "host:port pinged periodically with this process's framework "
            "version (empty = off)", lambda v: True)
define_flag("trackme_interval_s", 300,
            "seconds between trackme pings", lambda v: int(v) > 0)
define_flag("trackme_min_version", "",
            "server side: versions below this answer severity=warn",
            lambda v: True)
define_flag("trackme_fatal_version", "",
            "server side: versions below this answer severity=fatal",
            lambda v: True)

SEV_OK = 0
SEV_WARN = 1
SEV_FATAL = 2


def _version_tuple(v: str):
    out = []
    for part in v.split("."):
        digits = "".join(ch for ch in part if ch.isdigit())
        out.append(int(digits or 0))
    return tuple(out)


def handle_trackme_query(ver: str) -> dict:
    """Server side: classify a reported version against the gates."""
    sev, msg = SEV_OK, ""
    fatal = str(get_flag("trackme_fatal_version", ""))
    warn = str(get_flag("trackme_min_version", ""))
    try:
        vt = _version_tuple(ver)
        if fatal and vt < _version_tuple(fatal):
            sev, msg = SEV_FATAL, f"version {ver} < fatal floor {fatal}"
        elif warn and vt < _version_tuple(warn):
            sev, msg = SEV_WARN, f"version {ver} < advised floor {warn}"
    except ValueError:
        sev, msg = SEV_WARN, f"unparsable version {ver!r}"
    return {"severity": sev, "message": msg, "server_version": __version__}


_task: Optional[PeriodicTask] = None


def start_trackme(server: Optional[str] = None,
                  interval_s: Optional[float] = None) -> bool:
    """Begin pinging the trackme server (explicit addr or the
    ``trackme_server`` flag).  Idempotent; returns False when no server
    is configured."""
    global _task
    addr = server or str(get_flag("trackme_server", ""))
    if not addr:
        return False
    if _task is not None:
        return True
    ivl = float(interval_s or get_flag("trackme_interval_s", 300))

    def ping():
        from .tools.rpc_view import fetch
        try:
            body = fetch(addr, f"trackme?ver={__version__}", timeout=5.0)
            reply = json.loads(body)
        except Exception as e:
            LOG.debug("trackme ping failed: %s", e)
            return
        sev = int(reply.get("severity", 0))
        if sev >= SEV_FATAL:
            LOG.error("TRACKME: %s", reply.get("message", ""))
        elif sev >= SEV_WARN:
            LOG.warning("TRACKME: %s", reply.get("message", ""))

    _task = PeriodicTask(ivl, ping, run_immediately=True)
    return True


def stop_trackme() -> None:
    global _task
    if _task is not None:
        _task.stop()
        _task = None
