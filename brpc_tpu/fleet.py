"""Fleet observability plane (ISSUE 19): live load reports, a fleet
registry any server can host, metric federation, and a per-node event
flight recorder.

Every observability layer before this one (native engine telemetry,
distributed rpcz, the /lm serving plane) is per-process.  This module
grows the stack one level of hierarchy — the substrate ROADMAP item 3's
watch:// controller and slot-aware routing will stand on:

- **load report** — a versioned snapshot of THIS node's live capacity:
  decode-slot availability, ``PageAllocator``/``HostPagePool``
  occupancy, per-tier SLO attainment deltas over the telemetry window,
  drain/lame-duck state, native loop busy ratio, recent flight-recorder
  events and recent rpcz trace roots.  Built by
  :func:`build_load_report` (entry-listed in the blocking linter — no
  sleeps, no untimed waits, no sockets), cached by
  :class:`FleetReportCache` so the KV.Probe tail, the /fleet self view
  and the cadence push all share ONE build per interval (the
  ``LmTelemetryCache`` discipline, ``builds`` is the test pin);
- **fleet registry** — :class:`FleetRegistry` +
  :class:`FleetRegistryService` (``Fleet.Register`` / ``Fleet.Report``
  / ``Fleet.Deregister`` / ``Fleet.List``): members push reports on a
  cadence (:class:`FleetReporter`), membership can be seeded from the
  existing ``file://`` naming lists, and a member whose report ages
  past TTL flips LOUDLY to ``stale`` (and records a
  ``fleet_member_stale`` event) instead of vanishing.  A draining
  member deregisters explicitly, so /fleet shows ``draining`` within
  one report interval;
- **metric federation** — one registry-side scrape merges the members'
  Prometheus families under an ``instance`` label
  (:meth:`FleetRegistry.federate`), with fleet-level SLO rollups and
  top-k outlier nodes; plus a fleet **trace index** (trace root →
  owning instance) so ``rpcz_stitch`` can locate the process holding a
  trace root instead of BFS-from-root-only;
- **flight recorder** — a bounded ring of structured operational
  events under the CLOSED :data:`FLEET_EVENTS` enum (drain, lame-duck,
  breaker trip, ``kv_handoff_failed``, evict/spill, restart, ...).
  :func:`record_event` is the lock-free write path (GIL-atomic deque
  append; entry-listed in the blocking linter), merged into one fleet
  timeline on /fleet for postmortems.

Everything here must stay importable without jax and without the
native engine — pure-Python bookkeeping, same as lm_telemetry.
"""

from __future__ import annotations

import itertools
import json
import threading
import weakref
from collections import deque
from time import monotonic as _mono_s
from time import time as _wall_s
from typing import Any, Dict, List, Optional, Tuple

from .butil.flags import define_flag, get_flag, watch_flag
from .butil.logging_util import LOG
from .bvar.multi_dimension import PassiveDimension
from .bvar.passive_status import PassiveStatus

define_flag("fleet_obs", True,
            "fleet observability master switch: flight recorder writes "
            "and load-report cadence pushes (flippable live; hot paths "
            "read a flag-cache, not the flags table)",
            validator=lambda v: isinstance(v, bool))
define_flag("fleet_report_interval_s", 1.0,
            "cadence of a member's load-report pushes to its fleet "
            "registry (also the /fleet 'within one interval' promise "
            "for drain visibility)",
            validator=lambda v: isinstance(v, (int, float)) and
            0.01 <= float(v) <= 3600.0)
define_flag("fleet_member_ttl_s", 5.0,
            "registry: a member whose newest report is older than this "
            "flips LOUDLY to 'stale' (kept on /fleet, never dropped)",
            validator=lambda v: isinstance(v, (int, float)) and
            0.1 <= float(v) <= 86400.0)
define_flag("fleet_events_ring", 256,
            "bounded ring of flight-recorder events kept per node",
            validator=lambda v: isinstance(v, int) and 0 < v <= 65536)

LOAD_REPORT_VERSION = 1

# ---------------------------------------------------------------------------
# Flight recorder: CLOSED operational-event enum + bounded ring
# ---------------------------------------------------------------------------

# CLOSED enum (tools/check/enums.py pins every member to a test): one
# name per operational event class worth a postmortem timeline row.
# No "unknown" bucket — an unregistered event fails the assert at the
# first record_event call.
FLEET_EVENTS = (
    "fleet_restart",            # a Server began serving (fresh or hot restart)
    "fleet_drain",              # Server.drain() entered on this node
    "fleet_lame_duck",          # lame-duck signaling raised (drain grace)
    "fleet_stop",               # Server.stop() — node left the fleet
    "fleet_register",           # this node registered with a fleet registry
    "fleet_deregister",         # this node deregistered (drain-time, explicit)
    "fleet_member_stale",       # registry: a member's report aged past TTL
    "fleet_breaker_trip",       # client circuit breaker isolated a peer
    "fleet_kv_handoff_failed",  # strict at-most-once KV handoff closed a stream
    "fleet_kv_evict",           # paged-KV allocator evicted/refused under pressure
    "fleet_host_spill",         # a session's KV pages spilled to the host tier
)

_live = [bool(get_flag("fleet_obs"))]
watch_flag("fleet_obs", lambda v: _live.__setitem__(0, bool(v)))

_ev_seq = itertools.count(1)
_ev_counts: Dict[str, int] = {e: 0 for e in FLEET_EVENTS}
_events: deque = deque(maxlen=int(get_flag("fleet_events_ring")))


def record_event(event: str, detail: str = "") -> None:
    """Append one structured operational event to the bounded ring.

    The write path is lock-free — a GIL-atomic ``deque.append`` plus a
    plain counter bump (racy-but-monotonic for readers, the engine-
    telemetry discipline) — because callers include ``Server.drain``
    and the KV eviction path.  Entry-listed in the blocking linter.
    """
    assert event in _ev_counts, f"unnamed fleet event {event!r}"
    if not _live[0]:
        return
    _ev_counts[event] += 1
    _events.append((next(_ev_seq), _wall_s(), event, str(detail)[:200]))


def event_counters() -> Dict[str, int]:
    return dict(_ev_counts)


def recent_events(limit: int = 64) -> List[dict]:
    """Newest-last slice of the flight recorder as portable dicts."""
    rows = list(_events)
    if limit and len(rows) > limit:
        rows = rows[-limit:]
    return [{"seq": s, "wall_s": round(w, 3), "event": e, "detail": d}
            for (s, w, e, d) in rows]


# ---------------------------------------------------------------------------
# Load report: one node's live capacity, versioned and portable
# ---------------------------------------------------------------------------

_report_seq = itertools.count(1)
_proc_start_s = _wall_s()


def _instance_of(server) -> str:
    ep = getattr(server, "listen_endpoint", None) if server is not None \
        else None
    return str(ep) if ep is not None else ""


def _slots_of(server) -> Optional[dict]:
    """Decode-slot availability from an LM service's batcher, if this
    server hosts one (the /lm scan, minus the portal)."""
    if server is None:
        return None
    for (_svc, mth), entry in sorted(
            getattr(server, "methods", {}).items()):
        if mth == "Decode" and hasattr(entry.service, "batcher"):
            try:
                bat = entry.service.batcher()
            except Exception:
                return None
            if bat is None:
                return None
            total = int(getattr(bat, "slots", 0) or 0)
            live = int(bat.live_slots())
            return {"live": live, "total": total,
                    "free": max(total - live, 0),
                    "steps": int(bat.steps_run())}
    return None


def _kv_occupancy(server) -> Optional[dict]:
    """PageAllocator / HostPagePool occupancy via the batcher's
    kv_stats() — absent keys mean that tier isn't configured."""
    if server is None:
        return None
    for (_svc, mth), entry in sorted(
            getattr(server, "methods", {}).items()):
        if mth == "Decode" and hasattr(entry.service, "batcher"):
            try:
                bat = entry.service.batcher()
                stats = bat.kv_stats() if bat is not None else None
            except Exception:
                return None
            if not stats:
                return None
            out: Dict[str, Any] = {}
            for tier in ("alloc", "host", "prefix"):
                if tier in stats:
                    out[tier] = stats[tier]
            for k in ("spills", "resumes", "parked"):
                if k in stats:
                    out[k] = stats[k]
            return out or None
    return None


def _slo_deltas() -> dict:
    """Per-tier SLO attainment deltas over the lm_telemetry snapshot
    window — current behavior, not lifetime averages."""
    try:
        from .models.lm_telemetry import windowed_slo_deltas
        return windowed_slo_deltas()
    except Exception:
        return {}


def _busy_ratio(server) -> Optional[float]:
    """Max per-loop windowed busy ratio when the native bridge is
    live — the scalar the LB side cares about (one saturated loop
    stalls its pinned connections even if siblings idle)."""
    bridge = getattr(server, "_native_bridge", None) \
        if server is not None else None
    if bridge is None:
        return None
    try:
        ratios = bridge.telemetry.per_loop_busy_ratios()
        return round(max(ratios), 4) if ratios else None
    except Exception:
        return None


def _trace_roots(limit: int = 32) -> List[str]:
    """Hex trace ids whose ROOT span (parent_span_id == 0) lives in
    this process — the fleet trace index's raw material."""
    try:
        from .rpcz import global_span_store
        spans = global_span_store().recent(limit * 4)
    except Exception:
        return []
    out: List[str] = []
    seen = set()
    for sp in spans:
        if getattr(sp, "parent_span_id", None) == 0:
            tid = f"{sp.trace_id:x}"
            if tid not in seen:
                seen.add(tid)
                out.append(tid)
                if len(out) >= limit:
                    break
    return out


def build_load_report(server=None) -> dict:
    """One versioned load report for THIS node.

    Pure local bookkeeping — reads passively-maintained counters and
    snapshots only.  Entry-listed in the blocking linter: no sleeps,
    no untimed waits, no socket work may ever grow in here (cadence
    and transport live in :class:`FleetReporter`).
    """
    report = {
        "v": LOAD_REPORT_VERSION,
        "instance": _instance_of(server),
        "seq": next(_report_seq),
        "wall_s": round(_wall_s(), 3),
        "uptime_s": round(_wall_s() - _proc_start_s, 3),
        "drain": getattr(server, "drain_phase", "serving")
        if server is not None else "serving",
        "lame_duck": bool(getattr(server, "lame_duck_signal_on", False))
        if server is not None else False,
        "inflight": int(getattr(server, "inflight", 0) or 0)
        if server is not None else 0,
        "slots": _slots_of(server),
        "kv": _kv_occupancy(server),
        "slo": _slo_deltas(),
        "busy_ratio": _busy_ratio(server),
        "events": recent_events(16),
        "trace_roots": _trace_roots(),
    }
    return report


class FleetReportCache:
    """Short-TTL cache over :func:`build_load_report` so the KV.Probe
    tail, /fleet?self=1 and the cadence push share ONE build per
    interval.  ``builds`` counts actual constructions — the
    one-build-per-interval test pin (the ``LmTelemetryCache``
    discipline)."""

    def __init__(self, ttl_s: float = 0.25):
        self._ttl = ttl_s
        self._lock = threading.Lock()
        self._snap: Optional[dict] = None
        self._t = 0.0
        self.builds = 0

    def get(self, server=None) -> dict:
        with self._lock:
            now = _mono_s()
            if self._snap is None or now - self._t >= self._ttl:
                self.builds += 1
                self._snap = build_load_report(server)
                self._t = now
            return self._snap


_report_cache: Optional[FleetReportCache] = None
_report_cache_lock = threading.Lock()


def report_cache() -> FleetReportCache:
    global _report_cache
    with _report_cache_lock:
        if _report_cache is None:
            _report_cache = FleetReportCache()
        return _report_cache


# ---------------------------------------------------------------------------
# Fleet registry: TTL'd member table + trace index + federation
# ---------------------------------------------------------------------------

# member states as /fleet shows them (not a counted enum — states are
# DERIVED from report age + drain fields, never counted blindly)
MEMBER_OK = "ok"
MEMBER_DRAINING = "draining"
MEMBER_STALE = "stale"
MEMBER_SEEDED = "seeded"        # expected via file:// seed, no report yet

FLEET_MEMBER_STATES = (MEMBER_OK, MEMBER_DRAINING, MEMBER_STALE,
                       MEMBER_SEEDED)

_FED_TTL_S = 2.0                # federation scrape cache
_TOP_K = 3                      # outlier rows surfaced on /fleet


class _Member:
    __slots__ = ("instance", "report", "last_seen", "deregistered",
                 "stale_announced")

    def __init__(self, instance: str):
        self.instance = instance
        self.report: Optional[dict] = None
        self.last_seen = 0.0            # monotonic; 0 = never reported
        self.deregistered = False
        self.stale_announced = False


class FleetRegistry:
    """Member table any server can host.  Reports arrive via
    :meth:`ingest` (the Fleet.Register / Fleet.Report RPCs), membership
    can be pre-seeded from a ``file://`` naming list, and staleness is
    judged lazily at read time: a member whose newest report is older
    than TTL flips to ``stale`` LOUDLY (one ``fleet_member_stale``
    flight-recorder event per transition) and stays on /fleet."""

    def __init__(self, ttl_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}
        self._ttl = float(ttl_s if ttl_s is not None
                          else get_flag("fleet_member_ttl_s"))
        self._fed_lock = threading.Lock()
        self._fed_body: Optional[str] = None
        self._fed_t = 0.0
        self.fed_builds = 0

    @property
    def ttl_s(self) -> float:
        return self._ttl

    # -- membership --------------------------------------------------------

    def ingest(self, report: dict) -> int:
        """Accept one member load report; returns 0 ok / -1 rejected.
        Unknown future versions are accepted (fields are additive);
        reports without an instance are not addressable and refused."""
        if not isinstance(report, dict):
            return -1
        inst = str(report.get("instance") or "")
        if not inst or int(report.get("v", 0)) < 1:
            return -1
        with self._lock:
            m = self._members.get(inst)
            if m is None:
                m = self._members[inst] = _Member(inst)
            m.report = report
            m.last_seen = _mono_s()
            m.stale_announced = False
            # an explicit deregister wins until the member re-registers
            # with a serving report (restart after drain)
            if m.deregistered and report.get("drain") == "serving":
                m.deregistered = False
        return 0

    def deregister(self, instance: str, detail: str = "") -> int:
        """Mark a member as intentionally leaving (drain-time): /fleet
        flips it to ``draining`` immediately instead of letting the TTL
        age it into ``stale``."""
        with self._lock:
            m = self._members.get(str(instance))
            if m is None:
                return -1
            m.deregistered = True
        return 0

    def seed(self, targets) -> int:
        """Pre-register expected members ("host:port" strings) — they
        show as ``seeded`` until their first report lands."""
        n = 0
        with self._lock:
            for t in targets:
                t = str(t).strip()
                if t and t not in self._members:
                    self._members[t] = _Member(t)
                    n += 1
        return n

    def seed_from_url(self, url: str) -> int:
        """Seed from an existing ``file://`` naming list (one
        ``host:port`` per line, ``#`` comments) — the same files
        ``Server.publish`` maintains."""
        path = url[len("file://"):] if url.startswith("file://") else url
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError as e:
            LOG.warning("fleet seed: cannot read %s: %s", path, e)
            return 0
        targets = []
        for ln in lines:
            ln = ln.split("#", 1)[0].strip()
            if ln:
                targets.append(ln.split()[0])
        return self.seed(targets)

    def _state_of(self, m: _Member, now: float) -> str:
        if m.report is None:
            return MEMBER_SEEDED
        if m.deregistered or m.report.get("drain") in ("draining",
                                                       "stopped"):
            return MEMBER_DRAINING
        if now - m.last_seen > self._ttl:
            return MEMBER_STALE
        return MEMBER_OK

    def members(self) -> List[dict]:
        """Member rows with derived state; the stale transition is
        announced (once per transition) on the registry host's own
        flight recorder — TTL-ing out is an EVENT, not silence."""
        now = _mono_s()
        rows = []
        with self._lock:
            for m in sorted(self._members.values(),
                            key=lambda x: x.instance):
                state = self._state_of(m, now)
                if state == MEMBER_STALE and not m.stale_announced:
                    m.stale_announced = True
                    record_event("fleet_member_stale", m.instance)
                age = round(now - m.last_seen, 3) if m.last_seen else None
                rows.append({"instance": m.instance, "state": state,
                             "age_s": age, "report": m.report})
        return rows

    def member_counts(self) -> Dict[str, int]:
        counts = {s: 0 for s in FLEET_MEMBER_STATES}
        for row in self.members():
            counts[row["state"]] += 1
        return counts

    # -- trace index -------------------------------------------------------

    def trace_owners(self, trace_id_hex: str) -> List[str]:
        """Instances whose reports claim the ROOT span of this trace —
        rpcz_stitch starts its BFS there instead of from-root-only."""
        tid = str(trace_id_hex).lower().lstrip("0x") or "0"
        out = []
        with self._lock:
            for m in self._members.values():
                rep = m.report
                if rep and tid in (rep.get("trace_roots") or ()):
                    out.append(m.instance)
        return sorted(out)

    def trace_index(self) -> Dict[str, List[str]]:
        idx: Dict[str, List[str]] = {}
        with self._lock:
            for m in self._members.values():
                rep = m.report
                for tid in (rep.get("trace_roots") or ()) if rep else ():
                    idx.setdefault(tid, []).append(m.instance)
        return {t: sorted(v) for t, v in idx.items()}

    # -- fleet timeline + rollups -----------------------------------------

    def timeline(self, limit: int = 128) -> List[dict]:
        """One merged fleet timeline: every member's reported recent
        events plus the registry host's own ring, ordered by wall
        clock (member clocks — good enough for postmortems; rpcz skew
        annotation is the precise tool)."""
        rows: List[dict] = []
        with self._lock:
            for m in self._members.values():
                rep = m.report
                for ev in (rep.get("events") or ()) if rep else ():
                    row = dict(ev)
                    row["instance"] = m.instance
                    rows.append(row)
        for ev in recent_events(limit):
            row = dict(ev)
            row["instance"] = "(registry)"
            rows.append(row)
        rows.sort(key=lambda r: (r.get("wall_s", 0), r.get("seq", 0)))
        # dedupe rows a member re-reports across consecutive reports
        seen = set()
        out = []
        for r in rows:
            key = (r["instance"], r.get("seq"), r.get("event"))
            if key in seen:
                continue
            seen.add(key)
            out.append(r)
        return out[-limit:]

    def rollups(self) -> dict:
        """Fleet-level SLO rollup (summed per-tier window deltas) and
        top-k outlier nodes by busy ratio and by SLO miss share."""
        slo: Dict[str, Dict[str, int]] = {}
        busy: List[Tuple[float, str]] = []
        miss: List[Tuple[float, str]] = []
        slots_live = slots_total = 0
        for row in self.members():
            rep = row["report"]
            if not rep:
                continue
            for tier, verdicts in (rep.get("slo") or {}).items():
                dst = slo.setdefault(tier, {})
                for v, n in verdicts.items():
                    dst[v] = dst.get(v, 0) + int(n)
            if rep.get("busy_ratio") is not None:
                busy.append((float(rep["busy_ratio"]), row["instance"]))
            tot = ok = 0
            for verdicts in (rep.get("slo") or {}).values():
                for v, n in verdicts.items():
                    tot += int(n)
                    if v == "slo_ok":
                        ok += int(n)
            if tot:
                miss.append((1.0 - ok / tot, row["instance"]))
            sl = rep.get("slots")
            if sl:
                slots_live += int(sl.get("live", 0))
                slots_total += int(sl.get("total", 0))
        busy.sort(reverse=True)
        miss.sort(reverse=True)
        return {
            "slo": slo,
            "slots": {"live": slots_live, "total": slots_total},
            "top_busy": [{"instance": i, "busy_ratio": b}
                         for b, i in busy[:_TOP_K]],
            "top_slo_miss": [{"instance": i,
                              "miss_ratio": round(r, 4)}
                             for r, i in miss[:_TOP_K]],
        }

    # -- metric federation -------------------------------------------------

    def federate(self, fetch=None, timeout_s: float = 1.0) -> str:
        """One collector scrape: every live member's /metrics merged
        under an ``instance`` label, prefixed by the fleet rollups.
        Cached (one scrape sweep per interval) — a hot dashboard must
        not multiply into per-request fleet-wide scrapes."""
        with self._fed_lock:
            now = _mono_s()
            if self._fed_body is not None and \
                    now - self._fed_t < _FED_TTL_S:
                return self._fed_body
            self.fed_builds += 1
            body = self._federate_build(fetch or fetch_member_metrics,
                                        timeout_s)
            self._fed_body, self._fed_t = body, now
            return body

    def _federate_build(self, fetch, timeout_s: float) -> str:
        out: List[str] = []
        counts = self.member_counts()
        out.append("# TYPE fleet_members gauge")
        for state in FLEET_MEMBER_STATES:
            out.append('fleet_members{state="%s"} %d'
                       % (state, counts[state]))
        roll = self.rollups()
        out.append("# TYPE fleet_slo_window_total gauge")
        for tier, verdicts in sorted(roll["slo"].items()):
            for v, n in sorted(verdicts.items()):
                out.append('fleet_slo_window_total{tier="%s",'
                           'verdict="%s"} %d' % (tier, v, n))
        out.append("# TYPE fleet_decode_slots gauge")
        out.append('fleet_decode_slots{kind="live"} %d'
                   % roll["slots"]["live"])
        out.append('fleet_decode_slots{kind="total"} %d'
                   % roll["slots"]["total"])
        for row in self.members():
            if row["state"] in (MEMBER_STALE, MEMBER_SEEDED):
                continue            # loud absence: counted above, not scraped
            inst = row["instance"]
            try:
                body = fetch(inst, timeout_s=timeout_s)
            except Exception as e:
                LOG.info("fleet federate: scrape %s failed: %s", inst, e)
                continue
            out.append(_inject_instance_label(body, inst))
        return "\n".join(out) + "\n"


def _inject_instance_label(body: str, instance: str) -> str:
    """Rewrite one Prometheus exposition body so every sample carries
    ``instance="host:port"`` — the federation merge key.  Comment/TYPE
    lines pass through; malformed lines are dropped rather than
    forwarded corrupt."""
    esc = instance.replace("\\", r"\\").replace('"', r'\"')
    out = []
    for line in body.splitlines():
        s = line.strip()
        if not s:
            continue
        if s.startswith("#"):
            out.append(s)
            continue
        # name{labels} value | name value
        space = s.rfind(" ")
        if space <= 0:
            continue
        series, value = s[:space], s[space + 1:]
        if series.endswith("}") and "{" in series:
            name, labels = series[:-1].split("{", 1)
            merged = f'instance="{esc}"' + ("," + labels if labels
                                            else "")
            out.append(f"{name}{{{merged}}} {value}")
        else:
            out.append(f'{series}{{instance="{esc}"}} {value}')
    return "\n".join(out)


def fetch_member_metrics(instance: str, timeout_s: float = 1.0) -> str:
    """HTTP GET a member's local /metrics (the builtin portal rides
    the shared serving port)."""
    import http.client
    host, _, port = str(instance).rpartition(":")
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=timeout_s)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"/metrics on {instance}: {resp.status}")
        return data.decode("utf-8", "replace")
    finally:
        conn.close()


def fetch_member_report(instance: str, timeout_s: float = 1.0) -> dict:
    """Pull-on-demand path: HTTP GET a member's own load report from
    its /fleet?self=1 portal page."""
    import http.client
    host, _, port = str(instance).rpartition(":")
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=timeout_s)
    try:
        conn.request("GET", "/fleet?self=1&format=json")
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"/fleet on {instance}: {resp.status}")
        return json.loads(data.decode("utf-8", "replace"))
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Fleet RPC service (registry side) + cadence reporter (member side)
# ---------------------------------------------------------------------------

class FleetRegistryService:
    """``Fleet.*`` RPC surface over a :class:`FleetRegistry` — members
    register over RPC, same wire as everything else (the watch://
    controller of ROADMAP item 3 will push membership over this same
    service)."""

    def __init__(self, registry: Optional[FleetRegistry] = None):
        self.registry = registry or FleetRegistry()

    @classmethod
    def service_name(cls) -> str:
        return "Fleet"

    def Register(self, cntl, request):
        try:
            report = json.loads(bytes(request).decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            cntl.set_failed(400, "fleet: malformed report json")
            return b""
        if self.registry.ingest(report) != 0:
            cntl.set_failed(400, "fleet: unaddressable report")
            return b""
        return b"ok"

    def Report(self, cntl, request):
        # cadence pushes share the Register path: first report IS the
        # registration (crash-restart re-registers implicitly)
        return self.Register(cntl, request)

    def Deregister(self, cntl, request):
        inst = bytes(request).decode("utf-8", "replace").strip()
        self.registry.deregister(inst)
        return b"ok"

    def List(self, cntl, request):
        return json.dumps({"members": self.registry.members()},
                          default=str).encode("utf-8")


def host_registry(server, seed: Optional[str] = None,
                  ttl_s: Optional[float] = None) -> FleetRegistry:
    """Attach a fleet registry to ``server`` (add the Fleet service;
    /fleet and /metrics?fleet=1 discover it through the service
    table).  Call before ``start()``."""
    reg = FleetRegistry(ttl_s=ttl_s)
    if seed:
        reg.seed_from_url(seed)
    if server.add_service(FleetRegistryService(reg)) != 0:
        raise RuntimeError("fleet: could not add Fleet service")
    _note_registry(reg)
    return reg


def registry_of(server) -> Optional[FleetRegistry]:
    svc = getattr(server, "_services", {}).get("Fleet")
    return getattr(svc, "registry", None) if svc is not None else None


# member-side reporters, keyed weakly so a dropped Server reaps its
# reporter without an unpublish protocol
_reporters: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class FleetReporter:
    """Pushes this server's load report to a registry on a cadence.

    The report itself comes from the shared :func:`report_cache` (one
    build per interval no matter how many consumers); only the
    transport lives here.  The loop thread is a daemon and wakes via a
    timed Event wait, so stop() and drain-time final pushes never
    block on a sleeping loop."""

    def __init__(self, server, registry_addr: str,
                 interval_s: Optional[float] = None):
        self._server_ref = weakref.ref(server)
        self.registry_addr = str(registry_addr)
        self.interval_s = float(interval_s if interval_s is not None
                                else get_flag("fleet_report_interval_s"))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._chan = None
        self._chan_lock = threading.Lock()
        self.pushes = 0
        self.push_failures = 0

    def _channel(self):
        with self._chan_lock:
            if self._chan is None:
                from .client import Channel
                ch = Channel()
                if ch.init(self.registry_addr) != 0:
                    raise RuntimeError(
                        f"fleet: bad registry addr {self.registry_addr}")
                self._chan = ch
            return self._chan

    def _call(self, method: str, payload: bytes,
              timeout_ms: int = 1000) -> bool:
        from .client import Controller
        try:
            cntl = Controller()
            cntl.timeout_ms = timeout_ms
            c = self._channel().call_method(method, payload, cntl=cntl)
            ok = not c.failed
        except Exception as e:
            LOG.info("fleet push failed: %s", e)
            ok = False
        self.pushes += 1
        if not ok:
            self.push_failures += 1
        return ok

    def push_now(self, method: str = "Fleet.Report",
                 fresh: bool = False) -> bool:
        """One bounded synchronous push.  ``fresh=True`` bypasses the
        snapshot cache — the drain path must not ship a pre-drain
        'serving' report that raced the state flip."""
        srv = self._server_ref()
        report = build_load_report(srv) if fresh \
            else report_cache().get(srv)
        return self._call(method, json.dumps(report,
                                             default=str).encode("utf-8"))

    def deregister_now(self) -> bool:
        srv = self._server_ref()
        inst = _instance_of(srv)
        if not inst:
            return False
        return self._call("Fleet.Deregister", inst.encode("utf-8"))

    def start(self) -> None:
        if self._thread is not None:
            return
        record_event("fleet_register", self.registry_addr)
        self.push_now("Fleet.Register")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-reporter")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not _live[0]:
                continue
            try:
                self.push_now()
            except Exception as e:     # never let the loop die silently
                LOG.warning("fleet reporter: %s", e)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


def attach_reporter(server, registry_addr: str,
                    interval_s: Optional[float] = None) -> FleetReporter:
    """Create + start this server's fleet reporter (idempotent per
    server — re-attach replaces)."""
    old = _reporters.get(server)
    if old is not None:
        old.stop()
    rep = FleetReporter(server, registry_addr, interval_s=interval_s)
    _reporters[server] = rep
    rep.start()
    return rep


def reporter_of(server) -> Optional[FleetReporter]:
    return _reporters.get(server)


# ---------------------------------------------------------------------------
# Server lifecycle wiring (server.py calls these, lazily imported)
# ---------------------------------------------------------------------------

def on_server_start(server) -> None:
    record_event("fleet_restart", _instance_of(server))


def on_server_drain(server) -> None:
    """Drain visibility within ONE report interval: record the drain
    (+ lame-duck) events, push a final report that already says
    ``draining``, then deregister — all bounded (1s RPC timeouts), so
    the drain grace budget is not consumed by observability."""
    inst = _instance_of(server)
    record_event("fleet_drain", inst)
    if getattr(server, "lame_duck_signal_on", False):
        record_event("fleet_lame_duck", inst)
    rep = _reporters.get(server)
    if rep is None:
        return
    # the cadence loop dies FIRST — a queued push of a pre-drain
    # 'serving' report after the deregister would flip the registry
    # right back to ok
    rep.stop()
    try:
        rep.push_now(fresh=True)
        rep.deregister_now()
        record_event("fleet_deregister", rep.registry_addr)
    except Exception as e:
        LOG.info("fleet drain dereg: %s", e)


def on_server_stop(server) -> None:
    record_event("fleet_stop", _instance_of(server))
    rep = _reporters.pop(server, None)
    if rep is not None:
        rep.stop()


# ---------------------------------------------------------------------------
# /vars + /metrics exposure
# ---------------------------------------------------------------------------

# the registry a /vars reader should describe: the most recently hosted
# one in this process (tests host several; last wins, weakly held)
_registry_ref = lambda: None            # noqa: E731 — rebound by _note_registry


def _note_registry(reg: FleetRegistry) -> None:
    global _registry_ref
    _registry_ref = weakref.ref(reg)


def _member_state_rows() -> Dict[str, int]:
    reg = _registry_ref()
    return reg.member_counts() if reg is not None \
        else {s: 0 for s in FLEET_MEMBER_STATES}


_events_var = PassiveDimension(("event",), event_counters,
                               name="fleet_events_total")
_members_var = PassiveDimension(("state",), _member_state_rows,
                                name="fleet_members")
_report_builds_var = PassiveStatus(
    lambda: report_cache().builds, name="fleet_report_builds")

_FLEET_VARS = (
    (_events_var, "fleet_events_total"),
    (_members_var, "fleet_members"),
    (_report_builds_var, "fleet_report_builds"),
)


def expose_fleet_variables() -> None:
    """Re-expose after a test registry wipe (``Variable.expose`` is a
    no-op while the name is still registered)."""
    for var, name in _FLEET_VARS:
        var.expose(name)


def _reset_for_tests(ring: Optional[int] = None) -> None:
    global _events, _report_cache, _registry_ref
    for k in _ev_counts:
        _ev_counts[k] = 0
    _events = deque(maxlen=int(ring) if ring
                    else int(get_flag("fleet_events_ring")))
    with _report_cache_lock:
        _report_cache = None
    _registry_ref = lambda: None
    _live[0] = bool(get_flag("fleet_obs"))
    expose_fleet_variables()
