"""Window / PerSecond views over reducers
(≈ /root/reference/src/bvar/window.h:43,174).

``Window(adder, 10)`` = value accumulated over the last 10 seconds.
``PerSecond(adder, 10)`` = that / 10.
"""

from __future__ import annotations

from typing import Optional

from .reducer import Adder, Maxer, Miner, IntRecorder, Reducer
from .sampler import ReducerSampler
from .variable import Variable


class Window(Variable):
    def __init__(self, reducer, window_size: int = 10,
                 name: Optional[str] = None):
        super().__init__()
        if window_size <= 0 or window_size > ReducerSampler.MAX_WINDOW:
            raise ValueError(f"window_size must be in [1, {ReducerSampler.MAX_WINDOW}]")
        self._reducer = reducer
        self.window_size = window_size
        if isinstance(reducer, (Maxer, Miner)):
            self._use_delta = False
            self._combine = reducer._op
            self._identity = reducer._identity
        elif isinstance(reducer, IntRecorder):
            self._use_delta = True
            self._combine = lambda a, b: (a[0] + b[0], a[1] + b[1])
            self._identity = (0, 0)
        else:
            self._use_delta = True
            self._combine = reducer._op
            self._identity = reducer._identity
        self._sampler = ReducerSampler.shared_for(reducer, self._use_delta)
        if name:
            self.expose(name)

    def get_value(self):
        samples = self._sampler.last_n(self.window_size)
        acc = self._identity
        for s in samples:
            acc = self._combine(acc, s)
        if isinstance(self._reducer, IntRecorder):
            s, n = acc
            return (s / n) if n else 0.0
        if isinstance(self._reducer, Maxer) and acc == float("-inf"):
            return 0
        if isinstance(self._reducer, Miner) and acc == float("inf"):
            return 0
        return acc


class PerSecond(Window):
    """Average per-second rate over the window (≈ bvar::PerSecond)."""

    def get_value(self):
        samples = self._sampler.last_n(self.window_size)
        if not samples:
            return 0
        acc = self._identity
        for s in samples:
            acc = self._combine(acc, s)
        return acc / len(samples)
