"""Bounded-rate sample collection (≈ /root/reference/src/bvar/collector.h):
shared by rpcz spans and rpc_dump.  Producers submit samples; a budget
limits samples/second globally; a background drainer hands batches to the
registered sink (preprocessor).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, List, Optional

COLLECTOR_SAMPLING_BASE = 64
_MAX_PER_SECOND = 1000


class Collected:
    """Base for collectable samples (≈ bvar::Collected LinkNode)."""

    def submit(self, collector: "Collector") -> None:
        collector.submit(self)


class Collector:
    def __init__(self, sink: Optional[Callable[[List[Collected]], None]] = None,
                 max_per_second: int = _MAX_PER_SECOND):
        self._sink = sink
        self._capacity = 4 * max_per_second
        self._queue: Deque[Collected] = deque()
        self._lock = threading.Lock()
        self._max_per_second = max_per_second
        self._second_start = time.monotonic()
        self._taken_this_second = 0
        self.dropped = 0

    def submit(self, sample: Collected) -> bool:
        """Rate-limited enqueue; returns False if over budget (dropped)."""
        now = time.monotonic()
        with self._lock:
            if now - self._second_start >= 1.0:
                self._second_start = now
                self._taken_this_second = 0
            if (self._taken_this_second >= self._max_per_second
                    or len(self._queue) >= self._capacity):
                # over rate budget OR drainer is lagging: refuse admission
                # (never silently evict a sample the producer was told we
                # accepted)
                self.dropped += 1
                return False
            self._taken_this_second += 1
            self._queue.append(sample)
        return True

    def drain(self) -> List[Collected]:
        """Grab everything pending (called by the dumping thread/portal)."""
        with self._lock:
            items = list(self._queue)
            self._queue.clear()
        if self._sink and items:
            self._sink(items)
        return items

    @property
    def pending(self) -> int:
        return len(self._queue)
