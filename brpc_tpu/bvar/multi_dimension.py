"""Labeled metrics (≈ /root/reference/src/bvar/multi_dimension.h, "mbvar"):
a map from label-value tuples to an underlying bvar, exported with labels to
Prometheus.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .variable import Variable


class MultiDimension(Variable):
    def __init__(self, labels: Sequence[str],
                 factory: Callable[[], Variable],
                 name: Optional[str] = None):
        super().__init__()
        self.labels = tuple(labels)
        self._factory = factory
        self._stats: Dict[Tuple[str, ...], Variable] = {}
        self._lock = threading.Lock()
        if name:
            self.expose(name)

    def get_stats(self, label_values: Sequence[str]) -> Variable:
        """Find-or-create the bvar for a label tuple."""
        key = tuple(str(v) for v in label_values)
        if len(key) != len(self.labels):
            raise ValueError(f"expected {len(self.labels)} label values, got {len(key)}")
        var = self._stats.get(key)
        if var is None:
            with self._lock:
                var = self._stats.get(key)
                if var is None:
                    var = self._factory()
                    self._stats[key] = var
        return var

    def has_stats(self, label_values: Sequence[str]) -> bool:
        return tuple(str(v) for v in label_values) in self._stats

    def delete_stats(self, label_values: Sequence[str]) -> None:
        with self._lock:
            self._stats.pop(tuple(str(v) for v in label_values), None)

    def count_stats(self) -> int:
        return len(self._stats)

    def items(self) -> List[Tuple[Tuple[str, ...], Variable]]:
        with self._lock:
            return list(self._stats.items())

    def get_value(self):
        return {k: v.get_value() for k, v in self.items()}

    def describe(self) -> str:
        return f"mbvar(labels={self.labels}, count={self.count_stats()})"


class _ConstVar:
    """Value row for PassiveDimension (get_value protocol only)."""

    __slots__ = ("_v",)

    def __init__(self, v=0):
        self._v = v

    def get_value(self):
        return self._v


class PassiveDimension(MultiDimension):
    """Labeled PASSIVE metric: rows come from a getter at read time
    instead of mutable sub-vars, so one shared snapshot (e.g. the
    native engine's telemetry table) feeds a whole labeled family;
    prometheus.py renders the rows as ``name{label="v"} value``
    exposition lines like any mbvar.  The getter returns
    ``{label_value_or_tuple: numeric}``."""

    def __init__(self, labels, getter, name: Optional[str] = None):
        super().__init__(labels, _ConstVar, name=name)
        self._getter = getter

    def items(self):
        try:
            rows = self._getter()
        except Exception:
            return []
        out = []
        for k, v in rows.items():
            key = (k,) if isinstance(k, str) \
                else tuple(str(x) for x in k)
            out.append((key, _ConstVar(v)))
        return out

    def get_value(self):
        try:
            return dict(self._getter())
        except Exception:
            return {}

    def describe(self) -> str:
        return str(self.get_value())
