"""Prometheus text exposition of the variable registry
(≈ /root/reference/src/brpc/builtin/prometheus_metrics_service.cpp).
"""

from __future__ import annotations

from typing import List

from .latency_recorder import LatencyRecorder
from .multi_dimension import MultiDimension
from .variable import _registry, _registry_lock


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return repr(float(value)) if isinstance(value, float) else str(value)
    return "0"  # non-numeric vars are skipped by caller


def _is_numeric(value) -> bool:
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def render_prometheus() -> str:
    with _registry_lock:
        items = list(_registry.items())
    lines: List[str] = []
    emitted = set()
    # composites first so their sub-view names take precedence over the
    # independently-exposed sub-vars (LatencyRecorder.expose registers both)
    items.sort(key=lambda kv: not isinstance(kv[1], LatencyRecorder))
    for name, var in items:
        if name in emitted:
            continue
        try:
            if isinstance(var, LatencyRecorder):
                emitted.update({f"{name}_latency", f"{name}_max_latency",
                                f"{name}_qps", f"{name}_count"})
                lines.append(f"# TYPE {name}_latency gauge")
                lines.append(f"{name}_latency {_fmt(var.latency())}")
                lines.append(f'{name}_latency{{quantile="0.5"}} {_fmt(var.p50())}')
                lines.append(f'{name}_latency{{quantile="0.9"}} {_fmt(var.p90())}')
                lines.append(f'{name}_latency{{quantile="0.99"}} {_fmt(var.p99())}')
                lines.append(f"# TYPE {name}_max_latency gauge")
                lines.append(f"{name}_max_latency {_fmt(var.max_latency())}")
                lines.append(f"# TYPE {name}_qps gauge")
                lines.append(f"{name}_qps {_fmt(var.qps())}")
                lines.append(f"# TYPE {name}_count counter")
                lines.append(f"{name}_count {_fmt(var.count())}")
            elif isinstance(var, MultiDimension):
                lines.append(f"# TYPE {name} gauge")
                for key, sub in var.items():
                    v = sub.get_value()
                    if _is_numeric(v):
                        labels = ",".join(
                            f'{ln}="{_escape_label(lv)}"'
                            for ln, lv in zip(var.labels, key))
                        lines.append(f"{name}{{{labels}}} {_fmt(v)}")
            else:
                v = var.get_value()
                if _is_numeric(v):
                    lines.append(f"# TYPE {name} gauge")
                    lines.append(f"{name} {_fmt(v)}")
        except Exception:
            continue
    return "\n".join(lines) + "\n"
