"""Variable trend recording — the live graphs behind /vars?expand=NAME.

≈ the reference portal's per-variable flot charts (vars_service.cpp +
js/flot): once a variable is expanded, a Sampler records its value every
second into a bounded ring; the portal renders the ring as an inline
SVG sparkline (self-contained — no JS assets).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from .sampler import Sampler, _sampler_thread
from .variable import find_exposed

WINDOW_SAMPLES = 120          # 2 minutes at 1Hz


class _TrendSampler(Sampler):
    def __init__(self, name: str):
        self.name = name
        self.ring: Deque[Tuple[float, float]] = deque(maxlen=WINDOW_SAMPLES)
        self.last_seen = time.monotonic()

    def take_sample(self) -> None:
        v = find_exposed(self.name)
        if v is None:
            return
        try:
            val = float(v.get_value())
        except (TypeError, ValueError):
            return
        self.ring.append((time.monotonic(), val))


_lock = threading.Lock()
_trends: Dict[str, _TrendSampler] = {}


def track(name: str) -> Optional[_TrendSampler]:
    """Start (or refresh) trend recording for an exposed variable."""
    if find_exposed(name) is None:
        return None
    with _lock:
        t = _trends.get(name)
        if t is None:
            t = _trends[name] = _TrendSampler(name)
            _sampler_thread.add(t)
        t.last_seen = time.monotonic()
        # lazily retire trends nobody has looked at for 10 minutes
        for k in [k for k, v in _trends.items()
                  if time.monotonic() - v.last_seen > 600]:
            _trends.pop(k, None)
    return t


def render_sparkline_svg(samples: List[Tuple[float, float]],
                         width: int = 480, height: int = 80) -> str:
    if len(samples) < 2:
        return ("<svg xmlns='http://www.w3.org/2000/svg' "
                f"width='{width}' height='{height}'>"
                "<text x='8' y='20' font-size='12'>collecting… "
                "refresh in a few seconds</text></svg>")
    vals = [v for _, v in samples]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    n = len(samples)
    pts = " ".join(
        f"{i * (width - 10) / (n - 1) + 5:.1f},"
        f"{height - 18 - (v - lo) / span * (height - 30):.1f}"
        for i, (_, v) in enumerate(samples))
    return (f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
            f"height='{height}' style='background:#fafafa;"
            f"border:1px solid #ddd'>"
            f"<polyline fill='none' stroke='#3366cc' stroke-width='1.5' "
            f"points='{pts}'/>"
            f"<text x='5' y='12' font-size='10'>max {hi:g}</text>"
            f"<text x='5' y='{height - 4}' font-size='10'>min {lo:g} · "
            f"{n}s window</text></svg>")
