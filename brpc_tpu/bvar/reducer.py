"""Thread-local-aggregated reducers (≈ /root/reference/src/bvar/reducer.h).

Write path is O(1) on a per-thread agent with no shared mutation; the read
path walks all agents and combines.  Agents of dead threads fold into a
residual at read time, so values are never lost to thread churn
(the reference's AgentGroup + combiner, src/bvar/detail/agent_group.h:51).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from .variable import Variable


class _Agent:
    __slots__ = ("value", "thread", "lock")

    def __init__(self, identity, thread):
        self.value = identity
        self.thread = thread
        # taken only in window mode (extremum windows): the sampler
        # reads-and-resets under the same lock writers combine under, so
        # no update can fall between two sampling epochs and vanish
        self.lock = threading.Lock()


class Reducer(Variable):
    """Combine per-thread values with an associative op."""

    def __init__(self, identity, op: Callable, name: Optional[str] = None):
        super().__init__()
        self._identity = identity
        self._op = op
        self._agents: List[_Agent] = []
        self._agents_lock = threading.Lock()
        self._residual = identity
        self._tls = threading.local()
        # Window-of-extremum support: when a Window attaches to a Maxer/
        # Miner it flips window-mode on; the sampler then drains (reads and
        # resets) agents each second, and drained values fold into
        # _residual so get_value() stays the all-time extremum.
        self._window_mode = False
        if name:
            self.expose(name)

    def _my_agent(self) -> _Agent:
        agent = getattr(self._tls, "agent", None)
        if agent is None:
            agent = _Agent(self._identity, threading.current_thread())
            with self._agents_lock:
                self._agents.append(agent)
            self._tls.agent = agent
        return agent

    def update(self, value) -> "Reducer":
        """O(1), contention-free: only touches this thread's agent.
        (Window mode adds an uncontended per-agent lock acquire.)"""
        agent = self._my_agent()
        if not self._window_mode:
            agent.value = self._op(agent.value, value)
            return self
        with agent.lock:
            agent.value = self._op(agent.value, value)
        return self

    def __lshift__(self, value) -> "Reducer":  # adder << 1, like the reference
        return self.update(value)

    def get_value(self):
        result = self._residual
        dead: List[_Agent] = []
        with self._agents_lock:
            agents = list(self._agents)
        for agent in agents:
            result = self._op(result, agent.value)
            if not agent.thread.is_alive():
                dead.append(agent)
        if dead:
            with self._agents_lock:
                for agent in dead:
                    if agent in self._agents:
                        self._residual = self._op(self._residual, agent.value)
                        self._agents.remove(agent)
        return result

    def enable_window_mode(self) -> None:
        self._window_mode = True

    def take_epoch_sample(self):
        """Close the current epoch: drain (read + reset) every agent under
        its lock and return the combined value.  Called by the sampler
        thread once per second in window mode.  Drained values fold into
        the residual so the plain ``get_value()`` remains the all-time
        aggregate."""
        cur = self._identity
        with self._agents_lock:
            for agent in self._agents:
                with agent.lock:
                    cur = self._op(cur, agent.value)
                    agent.value = self._identity
            self._residual = self._op(self._residual, cur)
            self._agents = [a for a in self._agents if a.thread.is_alive()]
        return cur


class Adder(Reducer):
    """adder << n; value = sum (≈ bvar::Adder, reducer.h:264)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(0, lambda a, b: a + b, name)


class Maxer(Reducer):
    """value = max (≈ bvar::Maxer, reducer.h:302)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(float("-inf"), lambda a, b: b if b > a else a, name)

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("-inf") else v


class Miner(Reducer):
    """value = min (≈ bvar::Miner, reducer.h:352)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(float("inf"), lambda a, b: b if b < a else a, name)

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("inf") else v


class IntRecorder(Variable):
    """Average of ints (≈ bvar::IntRecorder, recorder.h:84). The reference
    compresses (sum,num) into one int64 for atomicity; here each thread owns
    a (sum, num) pair and read-side merges."""

    def __init__(self, name: Optional[str] = None):
        super().__init__()
        self._sum = Adder()
        self._num = Adder()
        if name:
            self.expose(name)

    def update(self, value) -> "IntRecorder":
        self._sum.update(value)
        self._num.update(1)
        return self

    def __lshift__(self, value) -> "IntRecorder":
        return self.update(value)

    def average(self) -> float:
        n = self._num.get_value()
        return (self._sum.get_value() / n) if n else 0.0

    @property
    def sum(self):
        return self._sum.get_value()

    @property
    def num(self):
        return self._num.get_value()

    def get_value(self):
        return self.average()

    def get_sample(self) -> Tuple[int, int]:
        """(sum, num) cumulative snapshot for windowed delta sampling."""
        return self._sum.get_value(), self._num.get_value()
