"""Process/system metrics (≈ /root/reference/src/bvar/default_variables.cpp):
cpu, rss, fd count, thread count, uptime — read from /proc at query time.
"""

from __future__ import annotations

import os
import threading
import time

from .passive_status import PassiveStatus

_start_time = time.time()
_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except Exception:
        return 0


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except Exception:
        return 0


def _thread_count() -> int:
    return threading.active_count()


def _cpu_seconds() -> float:
    try:
        with open("/proc/self/stat") as f:
            raw = f.read()
        # comm (field 2) may contain spaces; fields resume after last ')'
        parts = raw.rsplit(")", 1)[1].split()
        utime, stime = int(parts[11]), int(parts[12])
        hz = os.sysconf("SC_CLK_TCK")
        return (utime + stime) / hz
    except Exception:
        return 0.0


def _uptime_s() -> float:
    return time.time() - _start_time


_exposed = []


def expose_default_variables() -> None:
    """Idempotently expose process_* vars (called by Server start).
    Keyed on registry state, not module state, so a registry reset
    (tests) can re-expose."""
    from .variable import find_exposed
    if find_exposed("process_pid") is not None:
        return
    _exposed.clear()
    _exposed.extend([
        PassiveStatus(_rss_bytes, "process_memory_resident"),
        PassiveStatus(_fd_count, "process_fd_count"),
        PassiveStatus(_thread_count, "process_thread_count"),
        PassiveStatus(_cpu_seconds, "process_cpu_seconds_total"),
        PassiveStatus(_uptime_s, "process_uptime_seconds"),
        PassiveStatus(os.getpid, "process_pid"),
    ])
