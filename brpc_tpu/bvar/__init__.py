"""bvar — thread-local-aggregated metrics (L2). SURVEY.md §2.3 inventory."""

from .variable import (Variable, find_exposed, list_exposed, count_exposed,
                       dump_exposed, clear_registry_for_tests, sanitize_name)
from .reducer import Adder, Maxer, Miner, IntRecorder, Reducer
from .window import Window, PerSecond
from .percentile import Percentile
from .latency_recorder import LatencyRecorder
from .passive_status import PassiveStatus, StatusVar
from .multi_dimension import MultiDimension
from .sampler import tick_once_for_tests, add_sampler, remove_sampler, Sampler
from .collector import Collector, Collected
from .prometheus import render_prometheus
from .default_variables import expose_default_variables
from .dump import dump_once, ensure_dumper
