"""LatencyRecorder — the composite every RPC method exposes
(≈ /root/reference/src/bvar/latency_recorder.h:75): windowed average
latency, max latency, qps, count, p50/p90/p99/p999.

Write path is FUSED: one thread-local agent carries (sum, num, max,
epoch-max, percentile reservoir), so recording a latency is a single TLS
lookup plus a handful of inline ops — not four separate reducer updates.
This matters because ``on_responded`` runs on every RPC: the reference's
IntRecorder/Percentile writes are tens of nanoseconds; the unfused
Python composite cost ~8µs/call, the fused one ~1µs.

Read path: lightweight component views subclass the plain reducer types
(IntRecorder/Maxer/Adder/Percentile) so the Window/PerSecond/sampler
machinery — which dispatches on isinstance and on the
get_sample/take_epoch_sample protocols — sees exactly the shapes it
expects while reading from the fused agents.

Epoch semantics: the per-second sampler drains epoch-max and the
reservoir with plain swaps (no per-update lock).  A sample landing
exactly on the swap boundary can miss one window bucket; cumulative
values (sum/num) never reset, so counts and averages are exact.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from .percentile import (SAMPLES_PER_SECOND, SAMPLES_PER_THREAD,
                         GlobalSample, Percentile)
from .reducer import Adder, IntRecorder, Maxer
from .variable import Variable
from .window import PerSecond, Window

_NEG_INF = float("-inf")


class _LatAgent:
    __slots__ = ("sum", "num", "mx", "epoch_mx", "samples", "scount",
                 "rng", "thread")

    def __init__(self, thread):
        self.sum = 0.0
        self.num = 0
        self.mx = _NEG_INF           # all-time max
        self.epoch_mx = _NEG_INF     # max since the last sampler drain
        self.samples: List[float] = []
        self.scount = 0
        # inline xorshift64 state for reservoir sampling: a
        # fast_rand_less_than() call per update costs more than the
        # whole rest of the fused write path
        self.rng = (id(thread) ^ 0x9E3779B97F4A7C15) | 1
        self.thread = thread


class _FusedLatency(IntRecorder):
    """IntRecorder view over the fused agents (cumulative sum/num)."""

    def __init__(self, owner: "LatencyRecorder"):
        Variable.__init__(self)
        self._owner = owner

    def update(self, value):             # pragma: no cover - not the path
        self._owner.update(value)
        return self

    def get_sample(self) -> Tuple[float, int]:
        return self._owner._sum_num()

    def get_value(self) -> float:
        s, n = self._owner._sum_num()
        return (s / n) if n else 0.0

    @property
    def sum(self):
        return self._owner._sum_num()[0]

    @property
    def num(self):
        return self._owner._sum_num()[1]

    def average(self) -> float:
        return self.get_value()


class _FusedMax(Maxer):
    """Maxer view: all-time max reads, per-epoch drains for Windows."""

    # Window reads these off the reducer (window.py:27-28)
    _identity = _NEG_INF
    _op = staticmethod(lambda a, b: b if b > a else a)

    def __init__(self, owner: "LatencyRecorder"):
        Variable.__init__(self)
        self._owner = owner

    def update(self, value):             # pragma: no cover - not the path
        self._owner.update(value)
        return self

    def enable_window_mode(self) -> None:
        pass                 # epoch max is always maintained inline

    def get_value(self):
        o = self._owner
        mx = o._res_mx
        for a in o._agents_snapshot():
            if a.mx > mx:
                mx = a.mx
        return 0 if mx == _NEG_INF else mx

    def take_epoch_sample(self):
        o = self._owner
        cur = o._res_epoch_mx          # dead threads' un-drained maxima
        o._res_epoch_mx = _NEG_INF
        for a in o._agents_snapshot():
            v = a.epoch_mx
            a.epoch_mx = _NEG_INF
            if v > cur:
                cur = v
        if cur > o._res_mx:
            o._res_mx = cur
        return cur


class _FusedCount(Adder):
    """Adder view over the fused update count (drives the qps window)."""

    # Window reads these off the reducer (window.py:34-36)
    _identity = 0
    _op = staticmethod(lambda a, b: a + b)

    def __init__(self, owner: "LatencyRecorder"):
        Variable.__init__(self)
        self._owner = owner

    def update(self, value):             # pragma: no cover - not the path
        self._owner.update(value)
        return self

    def get_value(self) -> int:
        return self._owner._sum_num()[1]


class _FusedPercentile(Percentile):
    """Percentile whose per-second merge drains the fused reservoirs."""

    def __init__(self, owner: "LatencyRecorder"):
        self._owner = owner
        Percentile.__init__(self)

    def update(self, value):             # pragma: no cover - not the path
        self._owner.update(value)
        return self

    def take_sample(self) -> None:
        o = self._owner
        merged = o._res_samples        # dead threads' un-drained samples
        o._res_samples = []
        count = o._res_scount
        o._res_scount = 0
        for a in o._agents_snapshot():
            s = a.samples
            a.samples = []
            c = a.scount
            a.scount = 0
            merged.extend(s)
            count += c
        if len(merged) > SAMPLES_PER_SECOND:
            step = len(merged) / SAMPLES_PER_SECOND
            merged = [merged[int(i * step)]
                      for i in range(SAMPLES_PER_SECOND)]
        with self._ring_lock:
            self._ring.push_force(GlobalSample(merged, count))


class LatencyRecorder(Variable):
    def __init__(self, name: Optional[str] = None, window_size: int = 10):
        super().__init__()
        self._tls = threading.local()
        self._agents: List[_LatAgent] = []
        self._agents_lock = threading.Lock()
        self._res_sum = 0.0
        self._res_num = 0
        self._res_mx = _NEG_INF
        # un-drained window data folded out of dead threads' agents:
        # consumed (and cleared) by the next epoch/percentile drain so a
        # thread dying mid-window loses nothing
        self._res_epoch_mx = _NEG_INF
        self._res_samples: List[float] = []
        self._res_scount = 0
        self._latency = _FusedLatency(self)
        self._max_latency = _FusedMax(self)
        self._count = _FusedCount(self)
        self._percentile = _FusedPercentile(self)
        self._latency_window = Window(self._latency, window_size)
        self._max_window = Window(self._max_latency, window_size)
        self._qps = PerSecond(self._count, window_size)
        self.window_size = window_size
        if name:
            self.expose(name)

    # -- fused write path --------------------------------------------------

    def update(self, latency_us: float) -> "LatencyRecorder":
        try:
            a = self._tls.a
        except AttributeError:
            a = _LatAgent(threading.current_thread())
            with self._agents_lock:
                self._agents.append(a)
            self._tls.a = a
        a.sum += latency_us
        a.num += 1
        if latency_us > a.mx:
            a.mx = latency_us
        if latency_us > a.epoch_mx:
            a.epoch_mx = latency_us
        n = a.scount + 1
        a.scount = n
        s = a.samples
        if len(s) < SAMPLES_PER_THREAD:
            s.append(latency_us)
        else:
            r = a.rng
            r ^= (r << 13) & 0xFFFFFFFFFFFFFFFF
            r ^= r >> 7
            r ^= (r << 17) & 0xFFFFFFFFFFFFFFFF
            a.rng = r
            idx = r % n                      # reservoir: uniform keep
            if idx < SAMPLES_PER_THREAD:
                s[idx] = latency_us
        return self

    def __lshift__(self, latency_us: float) -> "LatencyRecorder":
        return self.update(latency_us)

    # -- agent bookkeeping -------------------------------------------------

    def _agents_snapshot(self) -> List[_LatAgent]:
        with self._agents_lock:
            return list(self._agents)

    def _sum_num(self) -> Tuple[float, int]:
        """Cumulative (sum, num) over residual + live agents; folds dead
        threads' agents into the residual (values are never lost)."""
        s = self._res_sum
        n = self._res_num
        dead: List[_LatAgent] = []
        for a in self._agents_snapshot():
            s += a.sum
            n += a.num
            if not a.thread.is_alive():
                dead.append(a)
        if dead:
            with self._agents_lock:
                for a in dead:
                    if a in self._agents:
                        self._res_sum += a.sum
                        self._res_num += a.num
                        if a.mx > self._res_mx:
                            self._res_mx = a.mx
                        # keep the agent's un-drained window data for the
                        # next sampler drain (dropping it here silently
                        # zeroed windowed max/percentiles on thread churn)
                        if a.epoch_mx > self._res_epoch_mx:
                            self._res_epoch_mx = a.epoch_mx
                        self._res_samples.extend(a.samples)
                        self._res_scount += a.scount
                        self._agents.remove(a)
        return s, n

    # -- views --

    def latency(self) -> float:
        """Windowed average latency (us)."""
        return self._latency_window.get_value()

    def max_latency(self) -> float:
        return self._max_window.get_value()

    def qps(self) -> float:
        return self._qps.get_value()

    def count(self) -> int:
        return self._sum_num()[1]

    def latency_percentile(self, fraction: float) -> float:
        return self._percentile.get_number(fraction, self.window_size)

    def p50(self) -> float:
        return self.latency_percentile(0.5)

    def p90(self) -> float:
        return self.latency_percentile(0.9)

    def p99(self) -> float:
        return self.latency_percentile(0.99)

    def p999(self) -> float:
        return self.latency_percentile(0.999)

    def get_value(self):
        return self.latency()

    def describe(self) -> str:
        return (f"latency={self.latency():.0f} max={self.max_latency():.0f} "
                f"qps={self.qps():.1f} count={self.count()} "
                f"p99={self.p99():.0f}")

    def expose(self, name: str, prefix: str = "") -> bool:
        """Expose the composite's sub-views too (latency/qps/count/...)."""
        ok = super().expose(name, prefix)
        if ok and self._name:
            base = self._name
            self._latency_window.expose(f"{base}_latency")
            self._max_window.expose(f"{base}_max_latency")
            self._qps.expose(f"{base}_qps")
            self._count.expose(f"{base}_count")
        return ok
