"""LatencyRecorder — the composite every RPC method exposes
(≈ /root/reference/src/bvar/latency_recorder.h:75): windowed average
latency, max latency, qps, count, p50/p90/p99/p999.
"""

from __future__ import annotations

from typing import Optional

from .percentile import Percentile
from .reducer import Adder, IntRecorder, Maxer
from .variable import Variable
from .window import PerSecond, Window


class LatencyRecorder(Variable):
    def __init__(self, name: Optional[str] = None, window_size: int = 10):
        super().__init__()
        self._latency = IntRecorder()
        self._max_latency = Maxer()
        self._count = Adder()
        self._percentile = Percentile()
        self._latency_window = Window(self._latency, window_size)
        self._max_window = Window(self._max_latency, window_size)
        self._qps = PerSecond(self._count, window_size)
        self.window_size = window_size
        if name:
            self.expose(name)

    def update(self, latency_us: float) -> "LatencyRecorder":
        self._latency.update(latency_us)
        self._max_latency.update(latency_us)
        self._count.update(1)
        self._percentile.update(latency_us)
        return self

    def __lshift__(self, latency_us: float) -> "LatencyRecorder":
        return self.update(latency_us)

    # -- views --

    def latency(self) -> float:
        """Windowed average latency (us)."""
        return self._latency_window.get_value()

    def max_latency(self) -> float:
        return self._max_window.get_value()

    def qps(self) -> float:
        return self._qps.get_value()

    def count(self) -> int:
        return self._count.get_value()

    def latency_percentile(self, fraction: float) -> float:
        return self._percentile.get_number(fraction, self.window_size)

    def p50(self) -> float:
        return self.latency_percentile(0.5)

    def p90(self) -> float:
        return self.latency_percentile(0.9)

    def p99(self) -> float:
        return self.latency_percentile(0.99)

    def p999(self) -> float:
        return self.latency_percentile(0.999)

    def get_value(self):
        return self.latency()

    def describe(self) -> str:
        return (f"latency={self.latency():.0f} max={self.max_latency():.0f} "
                f"qps={self.qps():.1f} count={self.count()} "
                f"p99={self.p99():.0f}")

    def expose(self, name: str, prefix: str = "") -> bool:
        """Expose the composite's sub-views too (latency/qps/count/...)."""
        ok = super().expose(name, prefix)
        if ok and self._name:
            base = self._name
            self._latency_window.expose(f"{base}_latency")
            self._max_window.expose(f"{base}_max_latency")
            self._qps.expose(f"{base}_qps")
            self._count.expose(f"{base}_count")
        return ok
