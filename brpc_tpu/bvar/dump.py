"""Periodic bvar dump-to-file
(≈ /root/reference/src/bvar/variable.cpp:690-729: ``FLAGS_bvar_dump``
writes every exposed variable to ``bvar_dump_file`` each
``bvar_dump_interval`` seconds — the hook fleet monitors scrape).

Flags (live-tunable via /flags like the reference's reloadable gflags):

- ``bvar_dump``          master switch (off by default)
- ``bvar_dump_file``     target path; parent dirs are created
- ``bvar_dump_interval`` seconds between dumps
- ``bvar_dump_prefix``   only variables whose name starts with this

Writes are atomic (temp file + rename) so a scraper never reads a
half-written snapshot.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from .variable import dump_exposed

define_flag("bvar_dump", False,
            "periodically dump every exposed bvar to bvar_dump_file",
            validator=lambda v: True)
define_flag("bvar_dump_file", "monitor/bvar.data",
            "target file for the periodic bvar dump",
            validator=lambda v: bool(str(v)))
define_flag("bvar_dump_interval", 10,
            "seconds between bvar dumps",
            validator=lambda v: int(v) > 0)
define_flag("bvar_dump_prefix", "",
            "only dump variables whose exposed name starts with this",
            validator=lambda v: True)

_started = False
_start_lock = threading.Lock()
_dump_lock = threading.Lock()


def dump_once(path: Optional[str] = None) -> str:
    """Write one snapshot (atomically); returns the path written."""
    path = path or str(get_flag("bvar_dump_file", "monitor/bvar.data"))
    prefix = str(get_flag("bvar_dump_prefix", ""))
    snapshot = dump_exposed(prefix)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    # serialized + thread-tagged tmp: concurrent dump_once calls (the
    # periodic tick racing an on-demand dump) must never interleave
    # writes into one tmp file and promote a torn snapshot
    with _dump_lock:
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            for name in sorted(snapshot):
                f.write(f"{name} : {snapshot[name]}\n")
        os.replace(tmp, path)             # atomic snapshot swap
    return path


def ensure_dumper() -> None:
    """Start the periodic dump task (idempotent).  A no-op while the
    ``bvar_dump`` flag is off — call again after enabling it (servers
    call this on start, so the common path is: set the flag, start the
    server).  Once running, flipping the flag off pauses writes; the
    idle tick is a dict lookup every interval."""
    global _started
    if not get_flag("bvar_dump", False):
        return                  # nothing to run; retry after enabling
    with _start_lock:
        if _started:
            return
        _started = True
    from ..fiber.timer_thread import global_timer_thread

    def tick():
        try:
            if get_flag("bvar_dump", False):
                dump_once()
        except Exception as e:
            LOG.warning("bvar dump failed: %s", e)
        finally:
            global_timer_thread().schedule(
                tick, max(int(get_flag("bvar_dump_interval", 10)), 1))

    global_timer_thread().schedule(
        tick, max(int(get_flag("bvar_dump_interval", 10)), 1))
