"""Variable base + global registry (≈ /root/reference/src/bvar/variable.cpp).

A Variable is a named statistic. ``expose(name)`` registers it in the global
name→variable map; the HTTP portal's /vars, /brpc_metrics (Prometheus) and
dump-to-file all walk this registry.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

_registry: Dict[str, "Variable"] = {}
# RLock: dropping a registry reference can run Variable.__del__ → hide()
# on the same thread while the lock is held.
_registry_lock = threading.RLock()

_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    """Normalize to [a-zA-Z0-9_] the way the reference does for /vars."""
    return _NAME_SANITIZE_RE.sub("_", name.strip()).lower()


class Variable:
    """Base statistic. Subclasses implement get_value()/describe()."""

    def __init__(self):
        self._name: Optional[str] = None

    # -- registry --

    def expose(self, name: str, prefix: str = "") -> bool:
        full = sanitize_name(f"{prefix}_{name}" if prefix else name)
        with _registry_lock:
            if full in _registry:
                return False
            if self._name is not None:
                _registry.pop(self._name, None)
            _registry[full] = self
            self._name = full
            return True

    def expose_as(self, prefix: str, name: str) -> bool:
        return self.expose(name, prefix=prefix)

    def hide(self) -> bool:
        with _registry_lock:
            if self._name is None:
                return False
            _registry.pop(self._name, None)
            self._name = None
            return True

    @property
    def name(self) -> Optional[str]:
        return self._name

    # -- value access --

    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        return str(self.get_value())

    def __del__(self):
        try:
            self.hide()
        except Exception:
            pass


def find_exposed(name: str) -> Optional[Variable]:
    with _registry_lock:
        return _registry.get(sanitize_name(name))


def list_exposed() -> List[str]:
    with _registry_lock:
        return sorted(_registry.keys())


def count_exposed() -> int:
    with _registry_lock:
        return len(_registry)


def dump_exposed(filter_prefix: str = "") -> Dict[str, str]:
    """name → describe() snapshot of the whole registry (≈ /vars)."""
    with _registry_lock:
        items = list(_registry.items())
    out = {}
    for name, var in items:
        if filter_prefix and not name.startswith(filter_prefix):
            continue
        try:
            out[name] = var.describe()
        except Exception as e:  # a broken var must not break the dump
            out[name] = f"<error: {e}>"
    return out


def clear_registry_for_tests() -> None:
    with _registry_lock:
        _registry.clear()
