"""Percentile estimation (≈ /root/reference/src/bvar/detail/percentile.h).

Writes go to a per-thread bounded reservoir (no shared contention); the
sampler thread merges thread reservoirs into a per-second GlobalSample ring;
queries merge the last W seconds of global samples and read the quantile.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..butil.fast_rand import fast_rand_less_than
from ..butil.flat_map import BoundedQueue
from .sampler import Sampler, add_sampler
from .variable import Variable

SAMPLES_PER_THREAD = 254          # reference: PercentileInterval<254>
SAMPLES_PER_SECOND = 1024         # merged global reservoir size


class _ThreadReservoir:
    __slots__ = ("samples", "count", "thread")

    def __init__(self, thread):
        self.samples: List[float] = []
        self.count = 0
        self.thread = thread

    def add(self, value: float) -> None:
        self.count += 1
        if len(self.samples) < SAMPLES_PER_THREAD:
            self.samples.append(value)
        else:
            # reservoir sampling keeps the sample set uniform
            idx = fast_rand_less_than(self.count)
            if idx < SAMPLES_PER_THREAD:
                self.samples[idx] = value


class GlobalSample:
    __slots__ = ("samples", "count")

    def __init__(self, samples: List[float], count: int):
        self.samples = samples
        self.count = count


class Percentile(Variable, Sampler):
    def __init__(self, name: Optional[str] = None):
        Variable.__init__(self)
        self._tls = threading.local()
        self._reservoirs: List[_ThreadReservoir] = []
        self._lock = threading.Lock()
        self._ring = BoundedQueue(120)
        self._ring_lock = threading.Lock()
        add_sampler(self)
        if name:
            self.expose(name)

    def update(self, value: float) -> "Percentile":
        r = getattr(self._tls, "r", None)
        if r is None:
            r = _ThreadReservoir(threading.current_thread())
            with self._lock:
                self._reservoirs.append(r)
            self._tls.r = r
        r.add(value)
        return self

    def __lshift__(self, value: float) -> "Percentile":
        return self.update(value)

    def take_sample(self) -> None:
        """Merge all thread reservoirs into one per-second global sample."""
        merged: List[float] = []
        count = 0
        with self._lock:
            reservoirs = list(self._reservoirs)
            for r in reservoirs:
                merged.extend(r.samples)
                count += r.count
                r.samples = []
                r.count = 0
            self._reservoirs = [r for r in self._reservoirs
                                if r.thread.is_alive()]
        if len(merged) > SAMPLES_PER_SECOND:
            step = len(merged) / SAMPLES_PER_SECOND
            merged = [merged[int(i * step)] for i in range(SAMPLES_PER_SECOND)]
        with self._ring_lock:
            self._ring.push_force(GlobalSample(merged, count))

    def get_number(self, fraction: float, window_size: int = 10) -> float:
        """Quantile over the last window_size seconds of samples."""
        with self._ring_lock:
            recent = self._ring.snapshot()[-window_size:]
        samples: List[float] = []
        for gs in recent:
            samples.extend(gs.samples)
        if not samples:
            return 0.0
        samples.sort()
        idx = min(len(samples) - 1, int(fraction * len(samples)))
        return samples[idx]

    def get_value(self):
        return self.get_number(0.5)
