"""PassiveStatus / Status vars (≈ /root/reference/src/bvar/passive_status.h,
src/bvar/status.h): value-on-read callbacks and settable status values.
"""

from __future__ import annotations

from typing import Callable, Optional

from .variable import Variable


class PassiveStatus(Variable):
    """Value computed by a callback at read time."""

    def __init__(self, getter: Callable[[], object],
                 name: Optional[str] = None):
        super().__init__()
        self._getter = getter
        if name:
            self.expose(name)

    def get_value(self):
        return self._getter()


class StatusVar(Variable):
    """Settable value variable (≈ bvar::Status<T>)."""

    def __init__(self, value=None, name: Optional[str] = None):
        super().__init__()
        self._value = value
        if name:
            self.expose(name)

    def set_value(self, value) -> None:
        self._value = value

    def get_value(self):
        return self._value
