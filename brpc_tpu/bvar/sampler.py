"""The sampling thread (≈ /root/reference/src/bvar/detail/sampler.cpp).

One global daemon thread wakes every second and calls ``take_sample()`` on
every registered sampler.  Windows/PerSecond/Percentile build on the sampled
rings.  Tests can call :func:`tick_once_for_tests` to advance time
deterministically instead of sleeping.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import List, Optional

from ..butil.flat_map import BoundedQueue

SAMPLE_INTERVAL_S = 1.0


class Sampler:
    def take_sample(self) -> None:
        raise NotImplementedError


class _SamplerThread:
    """Holds samplers by weakref: a Window/Percentile that is dropped by
    its owner disappears from the schedule automatically — no unbounded
    growth of per-second work (the reference destroys samplers explicitly;
    GC is the Python-idiomatic equivalent)."""

    def __init__(self):
        self._samplers: List[weakref.ref] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._manual = False     # tests drive ticks; background thread idles
        self.rounds = 0

    def add(self, s: Sampler) -> None:
        with self._lock:
            self._samplers.append(weakref.ref(s))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="bvar_sampler", daemon=True)
                self._thread.start()

    def remove(self, s: Sampler) -> None:
        with self._lock:
            self._samplers = [r for r in self._samplers
                              if r() is not None and r() is not s]

    def tick(self) -> None:
        with self._lock:
            live = []
            samplers = []
            for r in self._samplers:
                s = r()
                if s is not None:
                    live.append(r)
                    samplers.append(s)
            self._samplers = live
        for s in samplers:
            try:
                s.take_sample()
            except Exception:
                pass
        self.rounds += 1

    def _run(self) -> None:
        while not self._stop.wait(SAMPLE_INTERVAL_S):
            if not self._manual:
                self.tick()


_sampler_thread = _SamplerThread()


def add_sampler(s: Sampler) -> None:
    _sampler_thread.add(s)


def remove_sampler(s: Sampler) -> None:
    _sampler_thread.remove(s)


def tick_once_for_tests() -> None:
    """Deterministically run one sampling round. The first call switches
    the process to manual sampling (the background thread stops ticking)
    so test windows can't be double-sampled by the 1s daemon."""
    _sampler_thread._manual = True
    _sampler_thread.tick()


def _sub(a, b):
    if isinstance(a, tuple):
        return tuple(x - y for x, y in zip(a, b))
    return a - b


_shared_sampler_lock = threading.Lock()


class ReducerSampler(Sampler):
    """Samples a reducer every second into a bounded ring.

    - For cumulative reducers (Adder/IntRecorder), stores per-second deltas
      computed by subtracting consecutive cumulative snapshots — the reducer
      itself is never reset, so cumulative reads (count()) stay valid.
    - For extremum reducers (Maxer/Miner), stores the per-epoch extremum
      via the reducer's epoch protocol (agents restart each second), so a
      windowed max really is the max over the window, while the reducer's
      own get_value() stays the all-time extremum.
    """

    MAX_WINDOW = 120

    def __init__(self, reducer, use_delta: bool):
        self._reducer = reducer
        self._use_delta = use_delta
        self._epoch_mode = (not use_delta) and hasattr(reducer, "take_epoch_sample")
        if self._epoch_mode:
            reducer.enable_window_mode()
        self._sample_fn = getattr(reducer, "get_sample", reducer.get_value)
        self._last = self._sample_fn() if use_delta else None
        self._ring = BoundedQueue(self.MAX_WINDOW)
        self._ring_lock = threading.Lock()
        add_sampler(self)

    @staticmethod
    def shared_for(reducer, use_delta: bool) -> "ReducerSampler":
        """One sampler per reducer (as in the reference): multiple Windows
        over the same reducer must share the ring — a second epoch-mode
        sampler would close every epoch twice and read zeros."""
        with _shared_sampler_lock:
            s = getattr(reducer, "_shared_sampler", None)
            if s is None:
                s = ReducerSampler(reducer, use_delta)
                reducer._shared_sampler = s
        return s

    def take_sample(self) -> None:
        if self._use_delta:
            cur = self._sample_fn()
            value = _sub(cur, self._last)
            self._last = cur
        elif self._epoch_mode:
            value = self._reducer.take_epoch_sample()
        else:
            value = self._sample_fn()
        with self._ring_lock:
            self._ring.push_force(value)

    def last_n(self, n: int) -> list:
        """Most recent up-to-n samples (oldest first)."""
        with self._ring_lock:
            items = self._ring.snapshot()
        return items[-n:]
