// Native IO engine — the C++ data plane under the Python framework.
//
// Role parity with the reference's C++ core runtime (SURVEY.md §2.4:
// Socket/EventDispatcher/InputMessenger): epoll event loops, connection
// ownership, tpu_std frame cutting and vectored writes all run in C++
// with the GIL released; Python is entered once per complete message
// (service dispatch), receiving zero-copy buffer views.
//
// Capability mapping (fresh design, not a port):
//   - EventDispatcher (event_dispatcher_epoll.cpp:59)  -> Loop (epoll)
//   - Socket read path (socket.cpp:1994 DoRead)        -> Conn::on_readable
//     with direct-into-message-buffer reads for large bodies
//   - InputMessenger cut loop (input_messenger.cpp:329) -> parse_frames
//   - Socket write queue + KeepWrite (socket.cpp:1575) -> Conn write
//     queue drained by the owning loop, EPOLLOUT-armed on EAGAIN
//
// Protocols cut natively: tpu_std ("TRPC") frames and ICI ack ("TICI")
// frames.  Anything else on a native-engine port is handed to Python as
// an UNKNOWN event (the bridge answers/fails it) — the full
// multi-protocol port lives on the Python path.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <unordered_set>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// NativeBuf: a Python object owning a malloc'd region, exposing the
// buffer protocol so Python/IOBuf can view it zero-copy.
// ---------------------------------------------------------------------------

typedef struct {
  PyObject_HEAD char* data;
  Py_ssize_t size;
  Py_ssize_t cap;   // allocation size (power-of-2 bucket)
} NativeBuf;

// Free-list of data blocks, bucketed by power-of-2 size.  All
// nativebuf_new/dealloc call sites hold the GIL, which serializes access
// — no lock needed.  Avoids mmap/munmap page-fault churn on the >128KB
// allocations glibc would otherwise hand straight back to the kernel
// (1MB attachment echoes pay ~256 soft faults per call without this).
constexpr int kBuckets = 24;                    // up to 8MB cached
constexpr int kPerBucket = 4;
static char* g_freelist[kBuckets][kPerBucket];
static int g_freecount[kBuckets];

static int bucket_of(Py_ssize_t size) {
  Py_ssize_t cap = 4096;
  int b = 12;
  while (cap < size && b < 63) { cap <<= 1; b++; }
  return b;
}

static void NativeBuf_dealloc(NativeBuf* self) {
  int b = bucket_of(self->cap);
  if (self->data && (Py_ssize_t(1) << b) == self->cap && b < kBuckets
      && g_freecount[b] < kPerBucket) {
    g_freelist[b][g_freecount[b]++] = self->data;
  } else {
    free(self->data);
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static int NativeBuf_getbuffer(NativeBuf* self, Py_buffer* view, int flags) {
  return PyBuffer_FillInfo(view, (PyObject*)self, self->data, self->size, 0,
                           flags);
}

static Py_ssize_t NativeBuf_length(NativeBuf* self) { return self->size; }

static PyBufferProcs NativeBuf_as_buffer = {
    (getbufferproc)NativeBuf_getbuffer,
    nullptr,
};

static PySequenceMethods NativeBuf_as_sequence = {
    (lenfunc)NativeBuf_length,
};

static PyTypeObject NativeBufType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

static NativeBuf* nativebuf_new(Py_ssize_t size) {
  NativeBuf* b = PyObject_New(NativeBuf, &NativeBufType);
  if (!b) return nullptr;
  int bk = bucket_of(size);
  Py_ssize_t cap;
  if (bk < kBuckets) {
    cap = Py_ssize_t(1) << bk;     // cacheable: power-of-2 bucket
    if (g_freecount[bk] > 0)
      b->data = g_freelist[bk][--g_freecount[bk]];
    else
      b->data = (char*)malloc(cap);
  } else {
    cap = size > 0 ? size : 1;     // beyond cache: exact, no 2x waste
    b->data = (char*)malloc(cap);
  }
  b->size = size;
  b->cap = cap;
  if (!b->data) {
    Py_DECREF(b);
    PyErr_NoMemory();
    return nullptr;
  }
  return b;
}

// ---------------------------------------------------------------------------
// Engine internals
// ---------------------------------------------------------------------------

constexpr uint32_t kHeaderSize = 12;  // "TRPC" + u32 body + u32 meta
constexpr uint32_t kAckHeader = 8;    // "TICI" + u32 count
constexpr size_t kInbufCap = 128 * 1024;
constexpr uint32_t kMaxBody = 512u * 1024u * 1024u;
// slim-lane attachment threshold: requests carrying more attachment
// bytes than this take the classic Python dispatch (the documented
// "attachments over threshold" fallback; large frames already fall
// back via the direct-read path)
constexpr uint32_t kSlimAttCap = 16 * 1024;

// dispatch event codes (Python side mirrors these)
enum : int {
  EV_OPEN = 0,
  EV_MESSAGE = 1,   // tpu_std frame: obj = NativeBuf(meta+payload), extra = meta_size
  EV_ACK = 2,       // TICI frame:    obj = NativeBuf(desc ids),     extra = count
  EV_UNKNOWN = 3,   // obj = NativeBuf(first bytes); conn will be closed
  EV_CLOSE = 4,
  EV_STREAM = 5,    // TSTR frame: obj = NativeBuf(flags+dest+len+payload)
  EV_HTTP = 6,      // one COMPLETE raw HTTP/1.x message (headers+body
                    // as received); Python parses + dispatches
  EV_BYTES = 7,     // passthrough gulp for protocols the engine does
                    // not cut (h2/gRPC, redis, thrift, ...): Python's
                    // InputMessenger registry cuts + dispatches
};

struct WriteItem {
  Py_buffer view;        // holds a ref on the producing Python object,
                         // UNLESS owned_str is set (view.obj is nullptr
                         // then)
  size_t offset = 0;
  std::string* owned_str = nullptr;  // moved-in native burst buffer —
                                     // deleted on completion, no copy
};

// ---------------------------------------------------------------------------
// Native telemetry (always-on): per-lane fixed-bucket histograms,
// reason-coded fallback counters, burst/writev distributions and loop
// busy accounting.  All hot-path captures are PLAIN per-loop-thread
// counters (each Loop owns a LoopTelemetry; only its own thread writes
// it) — no atomics, no locks on the request path.  engine.telemetry()
// reads them racily from a GIL-holding thread and sums across loops:
// a snapshot may be a few increments stale, never torn in a way that
// matters (monotonic uint64 on x86).  This is the "RPC Considered
// Harmful" discipline: per-stage timing of the messaging pipeline, so
// the fastest lanes stay inspectable in production.
// ---------------------------------------------------------------------------

static int64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000 + ts.tv_nsec;
}

// log2 buckets: value v (us, or a count for the size distributions)
// lands in bucket bit_length(v) — bucket 0 holds zeros, bucket i
// covers [2^(i-1), 2^i).  20 buckets span 1us .. ~0.5s and 1 .. 512K
// items, the whole plausible range of both uses.
constexpr int kHistBuckets = 20;

struct Hist {
  uint64_t b[kHistBuckets] = {};
  uint64_t count = 0;
  uint64_t sum = 0;          // us (latency hists) or items (size hists)
  void add(uint64_t v) {
    int i = 0;
    uint64_t x = v;
    while (x > 0 && i < kHistBuckets - 1) { x >>= 1; i++; }
    b[i]++;
    count++;
    sum += v;
  }
};

// server-lane index for the per-stage histograms (LANE_STREAM is the
// kind-5 stream-OPEN path: the unary call that negotiates a stream,
// batched through flush_py_batch exactly like the kind-3 items)
enum Lane : int { LANE_RAW = 0, LANE_SLIM = 1, LANE_HTTP = 2,
                  LANE_STREAM = 3, kLanes = 4 };
static const char* kLaneNames[kLanes] = {"raw", "slim", "http", "stream"};

// Reason-coded fallbacks: every branch that routes a request OFF a
// native lane (kind 2/3 tpu_std, kind 4 HTTP) and onto the classic
// Python path increments exactly one of these.  The Python-side
// scatter_call screening keeps its own named counters
// (client/fast_call.py) — client lanes never reach the engine loops.
// CONTRACT (machine-checked): kFbNames below and the bridge's
// FB_REASON_NAMES mirror must track this enum member-for-member, and
// every name needs a test pin — `python -m brpc_tpu.tools.check`
// (tools/check/contracts.py) gates all three in tier-1.
enum FbReason : int {
  FB_RPC_DISPATCH_OFF = 0,   // native dispatch gated off (rpc_dump live)
  FB_RPC_META_TAG,           // controller-tier TLV / malformed meta
  FB_RPC_NO_METHOD,          // svc.mth not registered with the engine
  FB_RPC_ATT_OVER_CAP,       // kind-3 attachment above kSlimAttCap
  FB_RPC_LARGE_FRAME,        // kind-2/3 frame on the direct-read path
  FB_RPC_TRACE_RAW,          // explicit trace on a kind-0/1/2 method:
                             // only the Python path can record a span
                             // there (the kind-3/4 slim lanes carry
                             // trace context through the shim instead)
  FB_RPC_SHM_LANE,           // frame carries shm data-plane TLVs
                             // (offer/accept/release/descriptor): the
                             // Python dispatch owns ring negotiation
                             // and descriptor resolution
  FB_HTTP_SLIM_OFF,          // slim HTTP lane gated off
  FB_HTTP_MALFORMED_LINE,    // request line missing tokens
  FB_HTTP_VERSION,           // version not exactly "HTTP/1.1\r\n"
  FB_HTTP_NO_ROUTE,          // METHOD+path not registered
  FB_HTTP_EXPECT,            // Expect header present
  FB_HTTP_UPGRADE,           // Upgrade header present
  FB_HTTP_CONNECTION,        // Connection other than keep-alive
  FB_HTTP_TRANSFER_ENCODING, // Transfer-Encoding framing
  FB_HTTP_BAD_HEADER,        // LF-only endings / colon-less line
  FB_HTTP_LARGE_BODY,        // over-inbuf Content-Length (direct read)
  FB_HTTP_CHUNK_STREAM,      // over-inbuf chunked body (stream FSM)
  FB_HTTP_LAME_DUCK,         // server draining: the classic lane owns
                             // the response so it carries the
                             // x-lame-duck / Connection: close signal
  FB_REASONS
};
static const char* kFbNames[FB_REASONS] = {
    "rpc_dispatch_off",   "rpc_meta_tag",     "rpc_no_method",
    "rpc_att_over_cap",   "rpc_large_frame",  "rpc_trace_raw_lane",
    "rpc_shm_lane",
    "http_slim_off",
    "http_malformed_line", "http_version",    "http_no_route",
    "http_expect",        "http_upgrade",     "http_connection",
    "http_transfer_encoding", "http_bad_header", "http_large_body",
    "http_chunk_stream",  "http_lame_duck",
};

// per-route fallback reasons the header scan can attribute to a
// resolved route (the route lookup precedes the header walk)
enum RouteFb : int {
  RFB_EXPECT = 0, RFB_UPGRADE, RFB_CONNECTION, RFB_TE, RFB_BAD_HEADER,
  kRouteFb
};
static const char* kRouteFbNames[kRouteFb] = {
    "http_expect", "http_upgrade", "http_connection",
    "http_transfer_encoding", "http_bad_header",
};

// Kind-5 streaming-lane fallbacks: every TSTR frame or stream-open
// request that declines the native lane and rides the Python streaming
// path instead lands in exactly one of these (closed enum — no
// "unknown" bucket, same discipline as FbReason).  CONTRACT
// (machine-checked): kStreamFbNames and the Python mirror
// (server/stream_slim.STREAM_FB_NAMES) must track this enum
// member-for-member — tools/check gates all three in tier-1.
enum StreamFb : int {
  SFB_NO_SHIM = 0,     // no kind-5 capability: stream shim never
                       // registered (lane flag off, or the server has
                       // no eligible unary methods)
  SFB_NON_INLINE,      // server runs user code off the loop
                       // (usercode_inline false): the open must ride
                       // the fiber path, so the whole stream stays on
                       // the Python lane
  SFB_COMPRESSED,      // stream-open request carries the compress TLV:
                       // only the classic path can decompress
  SFB_CHUNK_OVERSIZE,  // TSTR frame (or open) too large for the burst
                       // batch: the direct-read path delivers it to
                       // the Python streaming lane whole
  SFB_DRAIN,           // server draining: the classic path owns the
                       // ELAMEDUCK rejection + lame-duck TLV
  SFB_UNREGISTERED,    // TSTR frame for a stream the engine does not
                       // own (pure-Python streams, closed streams,
                       // forged ids) — the Python dispatch's
                       // socket-binding guard arbitrates
  SFB_REASONS
};
static const char* kStreamFbNames[SFB_REASONS] = {
    "stream_no_shim",   "stream_non_inline",  "stream_compressed",
    "stream_chunk_oversize", "stream_drain",  "stream_unregistered",
};

// Data-plane copy accounting: every place the engine COPIES payload
// bytes between buffers (the wire recv/writev themselves are not
// copies in this ledger — they are the transfer) increments a stage
// counter, so the zero-copy invariant of the eligible paths is
// ASSERTED by tests instead of claimed by comments (ISSUE 6).  Spans
// under kDpFloor are framing/bookkeeping, not data-plane traffic.
enum DpStage : int {
  DP_INGEST = 0,    // wire bytes duplicated into a delivery buffer
  DP_SHIM,          // payload/attachment materialized for a shim call
  DP_SERIALIZE,    // response payload copied into the native burst
  DP_INGEST_SPILL,  // buffered-read prefix of a large frame moved into
                    // its direct-read buffer at the rendezvous switch —
                    // bounded by the 128KB inbuf per message, the same
                    // first-segments-inline concession brpc's RDMA
                    // rendezvous makes; kept out of the zero-copy
                    // eligibility assert (tests pin the OTHER stages)
  kDpStages
};
static const char* kDpNames[kDpStages] = {"ingest", "shim", "serialize",
                                          "ingest_spill"};
constexpr size_t kDpFloor = 4096;

struct LoopTelemetry {
  uint64_t fallbacks[FB_REASONS] = {};
  uint64_t sfallbacks[SFB_REASONS] = {};  // kind-5 streaming lane
  uint64_t dp_copies[kDpStages] = {};
  uint64_t dp_copy_bytes[kDpStages] = {};
  Hist queue[kLanes];   // frame parse -> batched shim entry (us)
  Hist shim[kLanes];    // shim entry -> item complete (us)
  Hist resid[kLanes];   // frame parse -> response build done (us)
  Hist burst;           // batched items per flush_py_batch
  Hist stream_burst;    // stream chunks per batched delivery entry
  uint64_t stream_chunks_in = 0;   // DATA/CLOSE frames consumed natively
  uint64_t stream_feedbacks = 0;   // credit feedback frames consumed
                                   // natively (zero GIL entries)
  Hist wiov;            // iovs coalesced per writev in conn_flush
  uint64_t busy_ns = 0; // loop body time (callbacks, parsing, writes)
  uint64_t idle_ns = 0; // time blocked in epoll_wait (busy-poll spin
                        // included: spinning is waiting, not work)
  uint64_t polls = 0;   // epoll_wait returns
  uint64_t spin_polls = 0;  // busy-poll spins that harvested events
                            // before the blocking epoll_wait
  uint64_t accepts = 0;     // conns accepted AND pinned by this loop
  uint64_t frames = 0;      // complete messages parsed by this loop
  uint64_t handoffs = 0;    // cross-loop handoff nodes consumed
  uint64_t wq_hwm = 0;  // write-queue items high-water mark
  uint64_t inbuf_hwm = 0;  // inbuf fill high-water mark (bytes)
};

struct Loop;
static inline void dp_copy(Loop* lp, DpStage stage, size_t n);

// Incremental chunked-body accumulation (ADVICE r5 #4): a chunked
// request outgrowing the inbuf streams its RAW bytes (headers + chunk
// framing, exactly as received — the EV_HTTP contract) into `acc`
// while this FSM tracks chunk boundaries across reads, so the message
// is bounded by http_max_body instead of the 128KB inbuf.  The phase
// walk mirrors http_walk_chunks below — a change to either MUST be
// mirrored in the other.
struct ChunkState {
  std::string acc;       // raw message bytes so far
  size_t cap = 0;        // header length + http_max_body at entry
  int phase = 0;         // 0 size-line, 1 data, 2 CR, 3 LF, 4 trailer
  size_t remaining = 0;  // data bytes left in the current chunk
  size_t line = 0;       // chars accumulated in the current line
  char first = 0;        // first char of the current trailer line
  char szline[34];       // current chunk-size line (hex + extensions)
};

struct Conn {
  int fd = -1;
  uint64_t id = 0;
  struct Loop* loop = nullptr;
  std::string peer_ip;
  int peer_port = 0;
  // close-after-flush: when closing is set the conn lingers until the
  // write queue drains (EPOLLOUT-armed) or this deadline passes —
  // short writev/EAGAIN must not truncate a final response
  int64_t close_deadline = 0;
  // HTTP sniff commitment (ADVICE r5 #5): 0 = prefix matched a method
  // token but the request line has not yet shown " HTTP/1." — the conn
  // must not be held by the HTTP cutter forever; 1 = committed.
  uint8_t http_state = 0;
  int64_t sniff_deadline = 0;   // armed while uncommitted bytes wait
  ChunkState* chunk = nullptr;  // in-flight over-inbuf chunked message

  // read state: fixed buffer, no zero-fill churn (vector::resize would
  // memset 64KB per recv)
  char* inbuf = nullptr;    // malloc(kInbufCap) on accept
  size_t in_start = 0;      // consumed prefix
  size_t in_end = 0;        // valid bytes end
  NativeBuf* msg = nullptr; // in-flight large message (direct reads)
  size_t msg_filled = 0;
  uint32_t msg_meta = 0;
  int msg_kind = EV_MESSAGE;
  // first bytes matched no natively-cut protocol: every subsequent
  // gulp goes to Python whole (EV_BYTES) for the protocol registry
  bool passthrough = false;

  // write state (mutex: send() is called from arbitrary Python threads)
  std::mutex wmu;
  std::deque<WriteItem> wq;
  bool want_out = false;
  bool closing = false;
  bool dead = false;
  // coalesced cross-loop flush pending: CAS false->true gates the
  // handoff post (one node per conn per loop iteration); the owning
  // loop resets it before flushing so a racing send re-posts
  std::atomic<bool> flush_queued{false};
  // frames parsed on this conn (owning-loop writes; racy reads from
  // telemetry are fine) — the loop-pinning tests key on it
  uint64_t frames = 0;

  // native-dispatch responses accumulated during the current read burst
  // (loop thread only); flushed as ONE owned WriteItem before any
  // Python dispatch on this conn and at burst end — a pipelined batch
  // of echo responses costs one writev
  std::string native_out;
};

// Cross-loop completion handoff: a mutex-free MPSC Treiber stack per
// loop.  Producers (GIL-holding completion threads — fiber completions,
// scatter/fan-out results, close requests — and foreign accept loops)
// CAS-push a node and wake the consumer loop; the consumer exchanges
// the whole head once per iteration, reverses for FIFO, and processes
// without ever taking a lock.  This replaces the round-9
// mutex+vector pending_out/pending_close pair: with one loop per core
// a contended mutex on every cross-loop response serializes exactly
// the path per-core sharding exists to unshare.
enum HandoffOp : int { HO_FLUSH = 0, HO_CLOSE = 1, HO_ADOPT = 2 };

struct HandoffNode {
  HandoffNode* next;
  uint64_t id;
  int op;
};

struct Loop {
  int epfd = -1;
  int wakefd = -1;
  std::thread thr;
  struct EngineImpl* eng = nullptr;
  int index = 0;
  // sharded-accept listener owned by THIS loop (SO_REUSEPORT path);
  // -1 = no own listener (single shared fd on loop 0, rr placement)
  int listen_fd = -1;
  // connections owned by this loop
  std::unordered_map<uint64_t, Conn*> conns;
  // cross-loop handoff inbox (lock-free MPSC; see HandoffNode above)
  std::atomic<HandoffNode*> handoff_head{nullptr};
  // conns in close-after-flush linger (owned-loop state, no lock)
  std::vector<uint64_t> lingering;
  // conns holding a sniffed-HTTP prefix not yet committed by the
  // " HTTP/1." marker (owned-loop state; swept on the epoll tick)
  std::vector<uint64_t> sniffing;
  // Py_buffer releases deferred until we hold the GIL anyway
  std::vector<Py_buffer> decrefs;
  std::mutex decref_mu;
  // always-on counters/histograms, written ONLY by this loop's thread
  LoopTelemetry tel;
};

static inline void dp_copy(Loop* lp, DpStage stage, size_t n) {
  if (n >= kDpFloor) {
    lp->tel.dp_copies[stage]++;
    lp->tel.dp_copy_bytes[stage] += (uint64_t)n;
  }
}

// A method the engine answers entirely in C++ (no GIL, no Python
// dispatch) — the tpu-native analogue of the reference's C++ builtin
// services.  Registered pre-listen; the map is read-only afterwards.
//
// kind 3 is the SLIM SERVER LANE for full (cntl, request) methods: the
// engine scans the meta, batches eligible requests, and enters Python
// ONCE per read burst calling
// handler(payload, att, cid, conn_id, dom, nonce, recv_ns, trace,
// timeout_ms, tenant) —
// trace is None or the request's (trace_id, span_id, parent_id);
// timeout_ms is TLV 13's remaining budget (None = absent; 0 =
// expired at arrival); tenant is None or TLV 22's identity bytes
// (per-tenant fair admission) —
// admission,
// MethodStatus accounting and rpcz span sampling live in that shim
// (server/slim_dispatch.py).  A buffer return is framed
// natively; None means the shim escalated to the classic Python
// completion (async methods, sampled spans, compressed/streamed
// responses) and the response leaves via Engine_send instead.
struct NativeMethod {
  int kind = 0;                  // 0 = echo, 1 = const, 2 = py raw,
                                 // 3 = slim full-method dispatch
  std::string const_data;             // kind=1 response payload
  PyObject* handler = nullptr;        // kind=2/3 Python callable
  // kind-5 STREAM-OPEN shim (server/stream_slim.py): a kind-3 method's
  // stream-negotiating variant — requests carrying the stream TLVs
  // dispatch here instead of `handler`, batched in the same burst
  PyObject* stream_handler = nullptr;
  std::atomic<uint64_t> count{0};     // answered natively
  std::atomic<uint64_t> errors{0};    // EREQUEST answers (malformed att)
  // kind-5 lane accounting (stream opens ride LANE_STREAM hists; the
  // hist-count == handled+errors invariant holds per lane)
  std::atomic<uint64_t> stream_opens{0};
  std::atomic<uint64_t> stream_errors{0};
  // per-method fallback attribution (reasons where the method is
  // already resolved); atomics: several loops may hit one method
  std::atomic<uint64_t> fb_att_over_cap{0};
  std::atomic<uint64_t> fb_large_frame{0};
  std::atomic<uint64_t> fb_trace_raw{0};
  std::atomic<uint64_t> fb_stream_open{0};  // opens declined to Python
};

// One kind-5 native stream: the engine owns the WRITE-side credit
// window (produced vs the peer's consumption feedback, both accounted
// here in C++ — the Python producer only ever blocks on `cv`) and
// consumes inbound TSTR frames for `sid` natively.  Registered by the
// stream-open shim after stream_accept; looked up per frame by the
// owning loop; shared_ptr so an unregister/conn-close cannot free it
// under a writer mid-wait.
struct NativeStream {
  uint64_t sid = 0;        // OUR stream id (inbound frames' dest)
  uint64_t peer_sid = 0;   // peer's id (outbound frames' dest)
  uint64_t conn_id = 0;    // pinned connection (forged-frame guard)
  uint64_t window = 0;     // peer's advertised receive window (bytes)
  std::mutex mu;
  std::condition_variable cv;
  uint64_t produced = 0;          // bytes written by our side
  uint64_t remote_consumed = 0;   // peer feedback (absolute)
  bool closed = false;
};

// An HTTP route the engine dispatches through the SLIM HTTP LANE
// (kind 4): the request line + headers of an eligible HTTP/1.1
// message are parsed in C++, the per-route shim
// (server/http_slim.py) runs admission/MethodStatus/rpcz in the
// burst's single batched GIL entry, and the engine serializes the
// (status, headers, body) return natively into the burst's coalesced
// writev.  Registered pre-listen; read-only afterwards.
struct HttpRoute {
  PyObject* handler = nullptr;
  std::atomic<uint64_t> count{0};     // requests through the slim lane
  std::atomic<uint64_t> errors{0};    // shim raised / bad return shape
  // per-route fallback attribution (header-scan rejects on a resolved
  // route); indexed by RouteFb
  std::atomic<uint64_t> fb[kRouteFb] = {};
};

// One buffered-path request bound for a kind=2/3 Python handler, or a
// kind-4 slim-HTTP request (hroute set).  The payload/dom/conn/query/
// ctype pointers aim into the connection's inbuf and are valid only
// until parse_frames returns — every exit path flushes the batch first.
struct PyRawItem {
  NativeMethod* m;
  uint64_t cid;
  const char* payload;   // body past the meta (payload ++ attachment);
                         // kind 4: the HTTP request body
  size_t plen;           // total body-after-meta length
  uint32_t att;          // attachment tail size
  const char* dom = nullptr;    // kind 3: request's ici-domain bytes
  uint32_t dom_len = 0;
  const char* conn = nullptr;   // kind 3: request's conn-nonce bytes
  uint32_t conn_len = 0;
  // kind 3: trace context TLVs (trace/span/parent) — handed to the
  // shim so traced requests stay on the slim lane
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_id = 0;
  // kind 3: remaining-deadline ms (TLV 13) — the shim anchors it at
  // t_parse and sheds queue-expired requests (deadline plane);
  // timeout_present distinguishes an explicit on-wire 0 (expired at
  // arrival) from an absent deadline
  uint32_t timeout_ms = 0;
  bool timeout_present = false;
  // kind 3: tenant identity bytes (TLV 22) — the shim's admission
  // stage keys per-tenant fair admission off it (overload plane)
  const char* ten = nullptr;
  uint32_t ten_len = 0;
  // kind-5 stream-open fields (stream_id != 0 selects the lane): the
  // client's stream id (TLV 12) and its advertised receive window
  // (TLV 14) — the shim accepts the stream, answers the grant in the
  // response meta, and registers the stream with the engine
  uint64_t stream_id = 0;
  uint32_t stream_window = 0;
  // kind-4 slim-HTTP fields (hroute != nullptr selects the lane)
  HttpRoute* hroute = nullptr;
  const char* query = nullptr;  // bytes after '?' in the request target
  uint32_t qlen = 0;
  const char* ctype = nullptr;  // Content-Type header value (raw)
  uint32_t ctlen = 0;
  const char* attsz = nullptr;  // x-rpc-attachment-size value (raw)
  uint32_t attszlen = 0;
  const char* tp = nullptr;     // traceparent header value (raw)
  uint32_t tplen = 0;
  const char* dl = nullptr;     // x-deadline-ms header value (raw) —
  uint32_t dllen = 0;           // the shim sheds queue-expired requests
  const char* xt = nullptr;     // x-tenant header value (raw) — the
  uint32_t xtlen = 0;           // shim's fair-admission tenant key
  // telemetry: CLOCK_MONOTONIC ns at frame parse (comparable with
  // Python's time.monotonic_ns — the shims backdate rpcz spans with it)
  int64_t t_parse = 0;
};

// One inbound stream chunk (DATA/CLOSE/RST) bound for the batched
// Python delivery: payload aims into the connection's inbuf and is
// valid only until parse_frames returns — every exit path flushes the
// stream batch alongside the PyRawItem batch.
struct StreamItem {
  uint64_t sid;          // OUR stream id (the frame's dest)
  int flags;
  const char* payload;
  size_t len;
};

struct EngineImpl {
  PyObject* dispatch = nullptr;  // callable(event, conn_id, obj, extra)
  std::vector<Loop*> loops;
  int listen_fd = -1;
  std::atomic<uint64_t> next_conn{1};
  std::atomic<bool> stopping{false};
  std::atomic<int> rr{0};
  // id -> loop index, guarded (send() resolves conns cross-thread)
  std::mutex cmu;
  std::unordered_map<uint64_t, Conn*> by_id;
  std::atomic<uint64_t> nmessages{0}, bytes_in{0}, bytes_out{0};
  // native dispatch: "svc\0mth" -> handler.  Mutated only before
  // listen(); loops read it lock-free.  The bool gates at runtime
  // (live rpc_dump capture must see every request -> Python path).
  std::unordered_map<std::string, NativeMethod*> native_methods;
  std::atomic<bool> native_dispatch{false};
  // slim HTTP lane: "METHOD\0path" -> route.  Mutated only before
  // listen(); loops read it lock-free.  The bool gates at runtime
  // (tests/bench flip it to compare lanes in one process).
  std::unordered_map<std::string, HttpRoute*> http_routes;
  std::atomic<bool> http_slim{false};
  // pre-encoded local ici-domain TLV (empty when ici is off): kind-3
  // responses answer a request's domain exchange with it, exactly like
  // rpc_dispatch._domain_tlv on the classic fast path.  Set by the
  // bridge before listen(); read-only afterwards.
  std::string domain_tlv;
  bool started = false;
  // optional busy-poll spin (us) before each blocking epoll_wait: the
  // loop burns its core polling for new events instead of paying the
  // sleep/wake scheduler round trip — the latency-tail knob
  // (engine_busy_poll_us flag; runtime-settable, relaxed reads)
  std::atomic<int> busy_poll_us{0};
  // true = the loops run on Python-created threads (bridge calls
  // run_loop from threading.Thread).  A thread whose datastack
  // carries a resident Python frame never munmaps its chunk, so the
  // per-wake Python dispatch skips the mmap + page-fault (~14us on
  // this box) that a frameless C thread pays on EVERY cold eval entry.
  bool external_loops = false;
  // HTTP body limit (mirrors protocol/http.py max_body_size; the
  // bridge syncs it at listen time and on live flag flips)
  std::atomic<size_t> http_max_body{64u * 1024u * 1024u};
  // operability plane: lame-duck drain mode (set_lame_duck).  0 = off;
  // 1 = accept pause only (listeners disarmed, fds kept for a hot-
  // restart successor); 2 = pause + SIGNAL: natively-built tpu_std
  // responses carry the lame-duck TLV (tag 23) and new kind-4 HTTP
  // matches decline to the classic lane (which owns the x-lame-duck /
  // Connection: close headers).
  std::atomic<int> lame_duck{0};
  // optional per-burst epilogue: called ONCE after each flush_py_batch
  // item loop (GIL already held) so the Python shims can flush
  // per-burst aggregated accounting (admitted counts, method samples)
  // instead of paying locked counters per item
  PyObject* burst_end = nullptr;
  // ---- kind-5 streaming lane ----
  // native stream table: OUR stream id -> stream state.  Mutated by
  // GIL-holding Python threads (register/unregister) and conn_destroy;
  // loops look frames up under the same short lock.  nstreams is the
  // lock-free existence check on the per-frame hot path.
  std::mutex smu;
  std::unordered_map<uint64_t, std::shared_ptr<NativeStream>> streams;
  std::atomic<size_t> nstreams{0};
  // 0 = lane off (no capability), 1 = on, 2 = declined because the
  // server runs user code off the loop (usercode_inline false) — the
  // bridge sets it so the fallback reason names WHY, not just that
  std::atomic<int> stream_mode{0};
  // batched chunk delivery: ONE call per read burst with every
  // DATA/CLOSE chunk of every stream on the loop —
  // callable(list[(sid, flags, payload_bytes)])
  PyObject* stream_chunks = nullptr;
  // write-side counters (producers run on arbitrary Python threads,
  // so these are engine-level atomics, unlike the per-loop counters)
  std::atomic<uint64_t> s_chunks_out{0};
  std::atomic<uint64_t> s_chunk_bytes_out{0};
  std::atomic<uint64_t> s_credit_stalls{0};   // writes that had to wait
  std::atomic<uint64_t> s_write_batches{0};   // stream_write_many calls
};

static int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

static inline void count_msg(EngineImpl* eng, Loop* lp, Conn* c) {
  eng->nmessages++;
  lp->tel.frames++;
  c->frames++;
}

// close-after-flush bound: a conn that cannot drain its write queue to
// a slow reader within this window is torn down anyway (≈ the
// reference's lingering close)
constexpr int64_t kCloseLingerMs = 5000;

static void flush_decrefs_locked_gil(Loop* lp) {
  std::vector<Py_buffer> local;
  {
    std::lock_guard<std::mutex> g(lp->decref_mu);
    local.swap(lp->decrefs);
  }
  for (auto& v : local) PyBuffer_Release(&v);
}

static void queue_decref(Loop* lp, Py_buffer* v) {
  std::lock_guard<std::mutex> g(lp->decref_mu);
  lp->decrefs.push_back(*v);
}

// release a completed item's backing.  Owned blocks need no GIL; Python
// views either release inline (gil_held) or defer via the loop's queue.
static void complete_item(Loop* lp, WriteItem& it, bool gil_held) {
  if (it.owned_str) {
    delete it.owned_str;
    it.owned_str = nullptr;
    return;
  }
  if (gil_held)
    PyBuffer_Release(&it.view);
  else
    queue_decref(lp, &it.view);
}

static void loop_wake(Loop* lp) {
  uint64_t one = 1;
  ssize_t r = write(lp->wakefd, &one, 8);
  (void)r;
}

// push one handoff node onto lp's MPSC stack and wake it.  Safe from
// any thread; the release CAS publishes the node's fields to the
// consumer's acquire exchange.
static void loop_post(Loop* lp, uint64_t id, int op) {
  HandoffNode* n = new (std::nothrow) HandoffNode{nullptr, id, op};
  if (!n) return;                       // OOM: drop; linger/close sweeps
  HandoffNode* h = lp->handoff_head.load(std::memory_order_relaxed);
  do {
    n->next = h;
  } while (!lp->handoff_head.compare_exchange_weak(
      h, n, std::memory_order_release, std::memory_order_relaxed));
  loop_wake(lp);
}

// one complete message parsed on lp for conn c — the single site the
// engine-wide, per-loop and per-conn (loop-pinning) counters share
static inline void count_msg(EngineImpl* eng, Loop* lp, Conn* c);

static void call_dispatch(EngineImpl* eng, Loop* lp, int event, uint64_t id,
                          PyObject* obj /* stolen or null */, long extra) {
  PyGILState_STATE gs = PyGILState_Ensure();
  flush_decrefs_locked_gil(lp);
  PyObject* o = obj ? obj : Py_None;
  if (!obj) Py_INCREF(Py_None);
  PyObject* r = PyObject_CallFunction(eng->dispatch, "iKNl", event,
                                      (unsigned long long)id, o, extra);
  if (!r) {
    PyErr_WriteUnraisable(eng->dispatch);
  } else {
    Py_DECREF(r);
  }
  PyGILState_Release(gs);
}

static void conn_destroy(EngineImpl* eng, Loop* lp, Conn* c, bool notify) {
  if (c->dead) return;
  c->dead = true;
  epoll_ctl(lp->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  {
    // serialize with Engine_send's inline writev (it holds wmu): the fd
    // must not be closed — and possibly reused by a new accept — while a
    // sender thread is mid-write on it
    std::lock_guard<std::mutex> g(c->wmu);
    close(c->fd);
    c->fd = -1;
  }
  lp->conns.erase(c->id);
  {
    std::lock_guard<std::mutex> g(eng->cmu);
    eng->by_id.erase(c->id);
  }
  if (eng->nstreams.load(std::memory_order_acquire) != 0) {
    // kind-5 streams pinned to this conn: close (producers blocked on
    // credit wake with -2) and drop from the table — the Python-side
    // Stream teardown rides the EV_CLOSE socket release as before
    std::lock_guard<std::mutex> g(eng->smu);
    for (auto it = eng->streams.begin(); it != eng->streams.end();) {
      if (it->second->conn_id == c->id) {
        {
          std::lock_guard<std::mutex> g2(it->second->mu);
          it->second->closed = true;
          it->second->cv.notify_all();
        }
        it = eng->streams.erase(it);
      } else {
        ++it;
      }
    }
    eng->nstreams.store(eng->streams.size(), std::memory_order_release);
  }
  // free pending writes + in-flight message under the GIL
  PyGILState_STATE gs = PyGILState_Ensure();
  {
    std::lock_guard<std::mutex> g(c->wmu);
    for (auto& it : c->wq) complete_item(lp, it, /*gil_held=*/true);
    c->wq.clear();
  }
  Py_XDECREF((PyObject*)c->msg);
  c->msg = nullptr;
  flush_decrefs_locked_gil(lp);
  PyGILState_Release(gs);
  if (notify) call_dispatch(eng, lp, EV_CLOSE, c->id, nullptr, 0);
  free(c->inbuf);
  delete c->chunk;
  delete c;
}

// try to flush the write queue; returns false on fatal error
static bool conn_flush(Loop* lp, Conn* c) {
  std::unique_lock<std::mutex> g(c->wmu);
  if (c->wq.size() > lp->tel.wq_hwm) lp->tel.wq_hwm = c->wq.size();
  while (!c->wq.empty()) {
    struct iovec iov[64];
    int n = 0;
    for (auto it = c->wq.begin(); it != c->wq.end() && n < 64; ++it, ++n) {
      iov[n].iov_base = (char*)it->view.buf + it->offset;
      iov[n].iov_len = it->view.len - it->offset;
    }
    lp->tel.wiov.add((uint64_t)n);
    ssize_t w = writev(c->fd, iov, n);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (!c->want_out) {
          c->want_out = true;
          struct epoll_event ev;
          // a lingering (close-after-flush) conn stops reading: new
          // requests after close are ignored and a level-triggered
          // EPOLLIN on unread peer bytes would spin the loop
          ev.events = (c->closing ? 0u : (uint32_t)EPOLLIN) | EPOLLOUT;
          ev.data.u64 = c->id;
          epoll_ctl(lp->epfd, EPOLL_CTL_MOD, c->fd, &ev);
        }
        return true;
      }
      if (errno == EINTR) continue;
      return false;
    }
    lp->eng->bytes_out += (uint64_t)w;
    size_t left = (size_t)w;
    while (left > 0 && !c->wq.empty()) {
      WriteItem& it = c->wq.front();
      size_t avail = it.view.len - it.offset;
      if (left >= avail) {
        left -= avail;
        complete_item(lp, it, /*gil_held=*/false);
        c->wq.pop_front();
      } else {
        it.offset += left;
        left = 0;
      }
    }
  }
  if (c->want_out) {
    c->want_out = false;
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.u64 = c->id;
    epoll_ctl(lp->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
  if (c->closing) return false;  // flushed everything; close now
  return true;
}

// ---------------------------------------------------------------------------
// Native dispatch: registered echo-class methods answered entirely in
// C++ — no GIL, no Python objects, responses coalesced per read burst.
// The tpu-native analogue of the reference's built-in C++ services and
// its 200-300ns handler discipline (docs/cn/benchmark.md:57).
// ---------------------------------------------------------------------------

struct MetaScan {
  uint64_t cid = 0;
  uint32_t att = 0;
  const char* svc = nullptr;
  uint32_t svc_len = 0;
  const char* mth = nullptr;
  uint32_t mth_len = 0;
  // tag 15/17 (ici domain / conn nonce): the raw kinds ignore them
  // (lane contract); the SLIM lane (kind 3) forwards them to the shim
  // (peer-domain learning / nonce pinning) and answers the domain
  // exchange with the engine's cached local-domain TLV
  const char* dom = nullptr;
  uint32_t dom_len = 0;
  const char* conn = nullptr;
  uint32_t conn_len = 0;
  // tags 9/10/11 (trace/span/parent): the SLIM lane (kind 3) forwards
  // the context to the shim so traced requests STAY on the fast path;
  // kinds 0/1/2 fall back (reason-coded) — no span machinery there
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_id = 0;
  // tag 13 (remaining-deadline ms): the SLIM lane forwards it to the
  // shim, which sheds the request when — measured against t_parse —
  // the budget expired in queue (deadline plane); raw kinds ignore it
  // (no controller to enforce or propagate it).  timeout_present
  // tells an explicit on-wire 0 apart from an absent tag.
  uint32_t timeout_ms = 0;
  bool timeout_present = false;
  // tags 18-21 (shm ring offer/accept/release/descriptor): ring
  // negotiation and descriptor resolution live in Python — the frame
  // takes the classic path under the NAMED rpc_shm_lane reason
  bool shm = false;
  // tag 22 (tenant identity): the SLIM lane forwards it to the shim's
  // admission stage (per-tenant fair admission, overload plane); raw
  // kinds ignore it — same lane contract as the deadline tag 13
  const char* ten = nullptr;
  uint32_t ten_len = 0;
  // tags 12/14 (stream id / stream receive window): a stream-OPEN
  // request — the kind-5 STREAM lane dispatches it to the method's
  // stream shim; every other kind declines under a named StreamFb
  // reason (the Python lane owns the open there)
  uint64_t stream_id = 0;
  uint32_t stream_window = 0;
  // tag 2 (compress): scanned only so a compressed stream open gets
  // its NAMED kind-5 reason — every lane still declines compressed
  // requests to the classic path (only it can decompress)
  bool compressed = false;
};

// Mirror of native_bridge._scan_request_meta: collect cid/att/svc/mth
// plus the trace context (9/10/11 — slim lane carries it through),
// tolerate timeout/ici-domain/conn-nonce (13/15/17), flag the shm
// data-plane tags (18-21), bail on anything controller-tier
// (compress, errors, auth, stream, desc).  CONTRACT (machine-checked):
// every case label and its `ln !=` width guard must match
// protocol/meta.py's _T_* registry — tools/check gates it in tier-1.
static bool scan_request_meta(const char* p, size_t len, MetaScan* out) {
  size_t off = 0;
  while (off < len) {
    if (off + 5 > len) return false;
    uint8_t tag = (uint8_t)p[off];
    uint32_t ln;
    memcpy(&ln, p + off + 1, 4);
    off += 5;
    if (ln > len || off + ln > len) return false;
    switch (tag) {
      case 1:
        if (ln != 8) return false;
        memcpy(&out->cid, p + off, 8);
        break;
      case 2:
        if (ln != 1) return false;
        out->compressed = true;  // named screening only — every native
        break;                   // kind still declines compressed frames
      case 3:
        if (ln != 4) return false;
        memcpy(&out->att, p + off, 4);
        break;
      case 4:
        out->svc = p + off;
        out->svc_len = ln;
        break;
      case 5:
        out->mth = p + off;
        out->mth_len = ln;
        break;
      case 9:
        if (ln != 8) return false;
        memcpy(&out->trace_id, p + off, 8);
        break;
      case 10:
        if (ln != 8) return false;
        memcpy(&out->span_id, p + off, 8);
        break;
      case 11:
        if (ln != 8) return false;
        memcpy(&out->parent_id, p + off, 8);
        break;
      case 12:
        if (ln != 8) return false;
        memcpy(&out->stream_id, p + off, 8);   // stream open: kind-5
        break;                                 // lane (or named decline)
      case 13:
        if (ln != 4) return false;
        memcpy(&out->timeout_ms, p + off, 4);  // remaining-deadline ms:
        out->timeout_present = true;
        break;              // safe for every lane; enforced by kind 3
      case 14:
        if (ln != 4) return false;
        memcpy(&out->stream_window, p + off, 4);  // open handshake:
        break;                                    // peer's recv window
      case 15:
        out->dom = p + off;
        out->dom_len = ln;
        break;
      case 17:
        out->conn = p + off;
        out->conn_len = ln;
        break;
      case 18:
      case 19:
      case 20:
      case 21:
        out->shm = true;    // shm data plane: classic path, named
        break;              // reason (ring state lives in Python)
      case 22:
        out->ten = p + off;  // tenant identity: enforced by the kind-3
        out->ten_len = ln;   // shim's admission stage; raw kinds ignore
        break;
      default:
        return false;       // controller-tier tag: Python path
    }
    off += ln;
  }
  return out->svc != nullptr && out->mth != nullptr;
}

static NativeMethod* find_native(EngineImpl* eng, const MetaScan& s) {
  std::string key;           // "svc\0mth" — SSO keeps short names heapless
  key.reserve(s.svc_len + 1 + s.mth_len);
  key.append(s.svc, s.svc_len);
  key.push_back('\0');
  key.append(s.mth, s.mth_len);
  auto it = eng->native_methods.find(key);
  return it == eng->native_methods.end() ? nullptr : it->second;
}

// append a success-response frame head (TRPC header + cid TLV +
// optional att TLV + optional extra pre-encoded meta TLVs) for a body
// of plen payload bytes — the single source of the response wire
// layout for both the buffered and the zero-copy (direct-read) native
// paths.  ``extra`` carries the kind-3 domain-exchange answer (the
// cached local ici-domain TLV), appended after the att TLV exactly
// like the classic fast path orders its meta.
// pre-encoded lame-duck TLV (tag 23, u8 1) — MUST mirror meta.py's
// LAME_DUCK_TLV: the drain signal natively-built responses carry
// while the engine is in set_lame_duck mode
static const char kDuckTlv[6] = {0x17, 0x01, 0x00, 0x00, 0x00, 0x01};

static void native_append_head(EngineImpl* eng, std::string& out,
                               uint64_t cid, uint32_t att, size_t plen,
                               const std::string* extra = nullptr) {
  char meta[22];
  uint32_t l8 = 8, l4 = 4;
  meta[0] = 1;
  memcpy(meta + 1, &l8, 4);
  memcpy(meta + 5, &cid, 8);
  uint32_t mlen = 13;
  if (att) {
    meta[13] = 3;
    memcpy(meta + 14, &l4, 4);
    memcpy(meta + 18, &att, 4);
    mlen = 22;
  }
  uint32_t xlen = extra ? (uint32_t)extra->size() : 0;
  uint32_t dlen =
      (eng && eng->lame_duck.load(std::memory_order_relaxed) >= 2) ? 6
                                                                   : 0;
  uint32_t full = mlen + xlen + dlen;
  uint32_t body = full + (uint32_t)plen;
  char hdr[12];
  memcpy(hdr, "TRPC", 4);
  memcpy(hdr + 4, &body, 4);
  memcpy(hdr + 8, &full, 4);
  out.append(hdr, 12);
  out.append(meta, mlen);
  if (xlen) out.append(*extra);
  if (dlen) out.append(kDuckTlv, 6);
}

// append one native response frame (cid + optional att TLV + body bytes)
static void native_respond(Conn* c, uint64_t cid, const char* payload,
                           size_t plen, uint32_t att) {
  native_append_head(c->loop->eng, c->native_out, cid, att, plen);
  if (plen) {
    dp_copy(c->loop, DP_SERIALIZE, plen);
    c->native_out.append(payload, plen);
  }
}

// native error response (cid + error code/text TLVs)
static void native_error(Conn* c, uint64_t cid, int32_t code,
                         const char* text) {
  uint32_t tlen = (uint32_t)strlen(text);
  std::string meta;
  char b[13];
  uint32_t l = 8;
  b[0] = 1;
  memcpy(b + 1, &l, 4);
  memcpy(b + 5, &cid, 8);
  meta.append(b, 13);
  b[0] = 6;
  l = 4;
  memcpy(b + 1, &l, 4);
  memcpy(b + 5, &code, 4);
  meta.append(b, 9);
  b[0] = 7;
  memcpy(b + 1, &tlen, 4);
  meta.append(b, 5);
  meta.append(text, tlen);
  if (c->loop->eng->lame_duck.load(std::memory_order_relaxed) >= 2)
    meta.append(kDuckTlv, 6);   // drain: error frames signal too
  uint32_t body = (uint32_t)meta.size(), mlen = body;
  char hdr[12];
  memcpy(hdr, "TRPC", 4);
  memcpy(hdr + 4, &body, 4);
  memcpy(hdr + 8, &mlen, 4);
  c->native_out.append(hdr, 12);
  c->native_out.append(meta);
}

// defined in the HTTP section below / after this function
static bool native_stage(Conn* c, WriteItem* follow);
static void http_slim_respond(Conn* c, long status, const char* hdr,
                              size_t hlen, const char* body, size_t blen);
static void http_slim_error(Conn* c, const char* text);

// Run one kind-4 slim-HTTP item: call the per-route shim and serialize
// its (status, headers, body) return natively.  Runs under the GIL,
// inside flush_py_batch's single per-burst acquisition.
//
// ORDER GUARD: a shim may complete out-of-band DURING the call
// (progressive heads, fast async finishes) — those writes go through
// engine.send straight into the write queue, so any slim responses
// already accumulated in native_out must be staged into the queue
// FIRST or the pipelined response order breaks (HTTP has no
// correlation id).  Staging is not flushing: the burst still leaves in
// one writev at burst end.
static void http_slim_item(Loop* lp, Conn* c, PyRawItem& it) {
  if (!c->native_out.empty()) native_stage(c, nullptr);
  dp_copy(lp, DP_SHIM, it.plen);
  PyObject* body = PyBytes_FromStringAndSize(it.payload, it.plen);
  PyObject* q = it.query
      ? PyBytes_FromStringAndSize(it.query, it.qlen) : nullptr;
  PyObject* ct = it.ctype
      ? PyBytes_FromStringAndSize(it.ctype, it.ctlen) : nullptr;
  PyObject* asz = it.attsz
      ? PyBytes_FromStringAndSize(it.attsz, it.attszlen) : nullptr;
  PyObject* conn = body ? PyLong_FromUnsignedLongLong(c->id) : nullptr;
  PyObject* rcv = conn
      ? PyLong_FromLongLong((long long)it.t_parse) : nullptr;
  PyObject* tp = it.tp
      ? PyBytes_FromStringAndSize(it.tp, it.tplen) : nullptr;
  PyObject* dl = it.dl
      ? PyBytes_FromStringAndSize(it.dl, it.dllen) : nullptr;
  PyObject* xt = it.xt
      ? PyBytes_FromStringAndSize(it.xt, it.xtlen) : nullptr;
  PyObject* r = nullptr;
  if (body && conn && rcv && (!it.query || q) && (!it.ctype || ct)
      && (!it.attsz || asz) && (!it.tp || tp) && (!it.dl || dl)
      && (!it.xt || xt))
    r = PyObject_CallFunctionObjArgs(it.hroute->handler, body,
                                     q ? q : Py_None, ct ? ct : Py_None,
                                     asz ? asz : Py_None, conn, rcv,
                                     tp ? tp : Py_None,
                                     dl ? dl : Py_None,
                                     xt ? xt : Py_None, nullptr);
  Py_XDECREF(body);
  Py_XDECREF(q);
  Py_XDECREF(ct);
  Py_XDECREF(asz);
  Py_XDECREF(conn);
  Py_XDECREF(rcv);
  Py_XDECREF(tp);
  Py_XDECREF(dl);
  Py_XDECREF(xt);
  if (!r) {
    // shim raised (or OOM building args): answer a plain 500 with the
    // exception text, keeping the keep-alive conn in sync
    char msg[160] = "http slim shim failed";
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    if (v) {
      PyObject* s = PyObject_Str(v);
      if (s) {
        const char* u = PyUnicode_AsUTF8(s);
        if (u) snprintf(msg, sizeof msg, "%.*s", 150, u);
        Py_DECREF(s);
      }
    }
    PyErr_Clear();
    Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
    it.hroute->errors++;
    http_slim_error(c, msg);
    return;
  }
  if (r == Py_None) {
    // completed (or will complete, for async methods) out-of-band
    // through the classic write path
    Py_DECREF(r);
    it.hroute->count++;
    return;
  }
  if (PyTuple_Check(r) && PyTuple_GET_SIZE(r) == 3) {
    long st = PyLong_AsLong(PyTuple_GET_ITEM(r, 0));
    Py_buffer hb = {}, bb = {};
    if ((st == -1 && PyErr_Occurred())
        || PyObject_GetBuffer(PyTuple_GET_ITEM(r, 1), &hb,
                              PyBUF_SIMPLE) != 0
        || PyObject_GetBuffer(PyTuple_GET_ITEM(r, 2), &bb,
                              PyBUF_SIMPLE) != 0) {
      PyErr_Clear();
      if (hb.obj) PyBuffer_Release(&hb);
      Py_DECREF(r);
      it.hroute->errors++;
      http_slim_error(c, "http slim shim returned a bad tuple");
      return;
    }
    http_slim_respond(c, st, (const char*)hb.buf, (size_t)hb.len,
                      (const char*)bb.buf, (size_t)bb.len);
    PyBuffer_Release(&hb);
    PyBuffer_Release(&bb);
    Py_DECREF(r);
    it.hroute->count++;
    return;
  }
  // pre-serialized full response bytes (classic-built escalations that
  // still must keep wire order): append verbatim
  Py_buffer vb = {};
  if (PyObject_GetBuffer(r, &vb, PyBUF_SIMPLE) == 0) {
    c->native_out.append((const char*)vb.buf, (size_t)vb.len);
    PyBuffer_Release(&vb);
    Py_DECREF(r);
    it.hroute->count++;
    return;
  }
  PyErr_Clear();
  Py_DECREF(r);
  it.hroute->errors++;
  http_slim_error(c, "http slim shim returned a non-buffer");
}

// Run one kind-2/3 batched item: call the raw handler / slim shim and
// build the response frame natively.  Runs under the GIL, inside
// flush_py_batch's single per-burst acquisition.
// Payload/attachment reach the handler as bytes copies — the source
// bytes live in the transient inbuf, and a handler that retains its
// argument must never observe them changing.
static void raw_slim_item(Loop* lp, Conn* c, PyRawItem& it) {
    size_t plen = it.plen - it.att;
    // shim args are private bytes copies (transient inbuf source)
    dp_copy(lp, DP_SHIM, plen);
    dp_copy(lp, DP_SHIM, (size_t)it.att);
    PyObject* r = nullptr;
    if (it.m->kind == 3) {
      // slim full-method dispatch: the shim gets BYTES (the classic
      // path hands parse_payload bytes too — handlers may .decode()),
      // plus cid and conn id so escalations can complete classically,
      // plus the request's ici domain/nonce bytes (peer-domain
      // learning / conn-nonce pinning, classic-path semantics), plus
      // the engine's receive timestamp (rpcz spans backdate to it)
      PyObject* pb = PyBytes_FromStringAndSize(it.payload, plen);
      PyObject* ab = nullptr;
      if (pb && it.att)
        ab = PyBytes_FromStringAndSize(it.payload + plen, it.att);
      PyObject* cid = pb ? PyLong_FromUnsignedLongLong(it.cid) : nullptr;
      PyObject* conn = cid ? PyLong_FromUnsignedLongLong(c->id) : nullptr;
      PyObject* dom = it.dom_len
          ? PyBytes_FromStringAndSize(it.dom, it.dom_len) : nullptr;
      PyObject* nonce = it.conn_len
          ? PyBytes_FromStringAndSize(it.conn, it.conn_len) : nullptr;
      PyObject* rcv = conn
          ? PyLong_FromLongLong((long long)it.t_parse) : nullptr;
      // trace context (tags 9/10/11) as one tuple — None on the
      // untraced hot path (no per-call tuple churn there)
      PyObject* tr = nullptr;
      if (it.trace_id)
        tr = Py_BuildValue("(KKK)", (unsigned long long)it.trace_id,
                           (unsigned long long)it.span_id,
                           (unsigned long long)it.parent_id);
      // remaining-deadline ms (None = TLV 13 absent; an int — 0
      // allowed, meaning expired-at-arrival — when present): the shim
      // anchors it at the t_parse timestamp it already receives and
      // sheds queue-expired requests before user code runs
      PyObject* tmo = it.timeout_present
          ? PyLong_FromUnsignedLong(it.timeout_ms) : nullptr;
      // tenant identity (TLV 22): the shim's admission stage keys
      // per-tenant fair admission off it — None on the common
      // untenanted path (no per-call bytes churn there)
      PyObject* ten = it.ten_len
          ? PyBytes_FromStringAndSize(it.ten, it.ten_len) : nullptr;
      if (pb && (it.att == 0 || ab) && cid && conn && rcv
          && (!it.timeout_present || tmo)
          && (it.dom_len == 0 || dom) && (it.conn_len == 0 || nonce)
          && (it.trace_id == 0 || tr) && (it.ten_len == 0 || ten))
        r = PyObject_CallFunctionObjArgs(it.m->handler, pb,
                                         ab ? ab : Py_None, cid, conn,
                                         dom ? dom : Py_None,
                                         nonce ? nonce : Py_None,
                                         rcv, tr ? tr : Py_None,
                                         tmo ? tmo : Py_None,
                                         ten ? ten : Py_None, nullptr);
      Py_XDECREF(pb);
      Py_XDECREF(ab);
      Py_XDECREF(cid);
      Py_XDECREF(conn);
      Py_XDECREF(dom);
      Py_XDECREF(nonce);
      Py_XDECREF(rcv);
      Py_XDECREF(tr);
      Py_XDECREF(tmo);
      Py_XDECREF(ten);
      if (r == Py_None) {
        // handled out-of-band: the shim completed (or will complete)
        // the RPC through the classic Python send path
        Py_DECREF(r);
        it.m->count++;
        return;
      }
    } else {
      // the @raw_method contract hands the handler MEMORYVIEWS (the
      // large-frame Python lane does too — same types either route);
      // they view private bytes copies, so a handler retaining its
      // argument can never observe the transient inbuf changing
      PyObject* pb = PyBytes_FromStringAndSize(it.payload, plen);
      PyObject* pv = pb ? PyMemoryView_FromObject(pb) : nullptr;
      Py_XDECREF(pb);                    // the view keeps its own ref
      PyObject* av = nullptr;
      if (pv && it.att) {
        PyObject* ab = PyBytes_FromStringAndSize(it.payload + plen,
                                                 it.att);
        av = ab ? PyMemoryView_FromObject(ab) : nullptr;
        Py_XDECREF(ab);
      }
      if (pv && (it.att == 0 || av))
        r = PyObject_CallFunctionObjArgs(it.m->handler, pv,
                                         av ? av : Py_None, nullptr);
      Py_XDECREF(pv);
      Py_XDECREF(av);
    }
    if (!r) {
      // handler raised (or OOM building args): answer EINTERNAL with
      // the exception text, like the Python raw lane does
      char msg[160] = "raw handler failed";
      PyObject *t, *v, *tb;
      PyErr_Fetch(&t, &v, &tb);
      if (v) {
        PyObject* s = PyObject_Str(v);
        if (s) {
          const char* u = PyUnicode_AsUTF8(s);
          if (u) snprintf(msg, sizeof msg, "%.*s", 150, u);
          Py_DECREF(s);
        }
      }
      PyErr_Clear();
      Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
      it.m->errors++;
      native_error(c, it.cid, 2001 /* EINTERNAL */, msg);
      return;
    }
    PyObject* resp = r;
    PyObject* ratt = nullptr;
    if (PyTuple_Check(r) && PyTuple_GET_SIZE(r) == 2) {
      resp = PyTuple_GET_ITEM(r, 0);
      ratt = PyTuple_GET_ITEM(r, 1);
      if (ratt == Py_None) ratt = nullptr;
    }
    Py_buffer rb = {}, ab = {};
    if (PyObject_GetBuffer(resp, &rb, PyBUF_SIMPLE) != 0
        || (ratt && PyObject_GetBuffer(ratt, &ab, PyBUF_SIMPLE) != 0)) {
      PyErr_Clear();
      if (rb.obj) PyBuffer_Release(&rb);
      Py_DECREF(r);
      it.m->errors++;
      native_error(c, it.cid, 2001,
                   "raw method returned non-bytes");
      return;
    }
    size_t ralen = ab.obj ? (size_t)ab.len : 0;
    // kind 3: a request that carried the ici-domain TLV gets the local
    // domain TLV back in the response meta (the classic fast path's
    // domain-exchange answer, rpc_dispatch._send_response)
    const std::string* extra =
        (it.m->kind == 3 && it.dom_len
         && !lp->eng->domain_tlv.empty())
            ? &lp->eng->domain_tlv : nullptr;
    native_append_head(lp->eng, c->native_out, it.cid, (uint32_t)ralen,
                       (size_t)rb.len + ralen, extra);
    dp_copy(lp, DP_SERIALIZE, (size_t)rb.len);
    dp_copy(lp, DP_SERIALIZE, ralen);
    if (rb.len) c->native_out.append((const char*)rb.buf, rb.len);
    if (ralen) c->native_out.append((const char*)ab.buf, ralen);
    PyBuffer_Release(&rb);
    if (ab.obj) PyBuffer_Release(&ab);
    Py_DECREF(r);
    it.m->count++;
}

// Run one kind-5 STREAM-OPEN item: call the method's stream shim
// (server/stream_slim.py — the interceptor-chain binding) and build
// the grant response natively.  Runs under the GIL, inside
// flush_py_batch's single per-burst acquisition.
//
// Return contract with the shim:
//   (payload, grant_meta_bytes)  success: grant TLVs (stream id +
//                                window) appended to the response meta,
//                                frame built natively
//   bytes / memoryview           success without a stream grant (the
//                                method declined to accept)
//   None                         escalated to the classic completion
static void stream_open_item(Loop* lp, Conn* c, PyRawItem& it) {
  size_t plen = it.plen - it.att;
  dp_copy(lp, DP_SHIM, plen);
  dp_copy(lp, DP_SHIM, (size_t)it.att);
  PyObject* r = nullptr;
  PyObject* pb = PyBytes_FromStringAndSize(it.payload, plen);
  PyObject* ab = nullptr;
  if (pb && it.att)
    ab = PyBytes_FromStringAndSize(it.payload + plen, it.att);
  PyObject* cid = pb ? PyLong_FromUnsignedLongLong(it.cid) : nullptr;
  PyObject* conn = cid ? PyLong_FromUnsignedLongLong(c->id) : nullptr;
  PyObject* dom = it.dom_len
      ? PyBytes_FromStringAndSize(it.dom, it.dom_len) : nullptr;
  PyObject* nonce = it.conn_len
      ? PyBytes_FromStringAndSize(it.conn, it.conn_len) : nullptr;
  PyObject* rcv = conn
      ? PyLong_FromLongLong((long long)it.t_parse) : nullptr;
  PyObject* tr = nullptr;
  if (it.trace_id)
    tr = Py_BuildValue("(KKK)", (unsigned long long)it.trace_id,
                       (unsigned long long)it.span_id,
                       (unsigned long long)it.parent_id);
  PyObject* tmo = it.timeout_present
      ? PyLong_FromUnsignedLong(it.timeout_ms) : nullptr;
  PyObject* ten = it.ten_len
      ? PyBytes_FromStringAndSize(it.ten, it.ten_len) : nullptr;
  PyObject* sid = rcv
      ? PyLong_FromUnsignedLongLong(it.stream_id) : nullptr;
  PyObject* swin = sid
      ? PyLong_FromUnsignedLong(it.stream_window) : nullptr;
  if (pb && (it.att == 0 || ab) && cid && conn && rcv && sid && swin
      && (!it.timeout_present || tmo)
      && (it.dom_len == 0 || dom) && (it.conn_len == 0 || nonce)
      && (it.trace_id == 0 || tr) && (it.ten_len == 0 || ten))
    r = PyObject_CallFunctionObjArgs(it.m->stream_handler, pb,
                                     ab ? ab : Py_None, cid, conn,
                                     dom ? dom : Py_None,
                                     nonce ? nonce : Py_None,
                                     rcv, tr ? tr : Py_None,
                                     tmo ? tmo : Py_None,
                                     ten ? ten : Py_None,
                                     sid, swin, nullptr);
  Py_XDECREF(pb);
  Py_XDECREF(ab);
  Py_XDECREF(cid);
  Py_XDECREF(conn);
  Py_XDECREF(dom);
  Py_XDECREF(nonce);
  Py_XDECREF(rcv);
  Py_XDECREF(tr);
  Py_XDECREF(tmo);
  Py_XDECREF(ten);
  Py_XDECREF(sid);
  Py_XDECREF(swin);
  if (!r) {
    char msg[160] = "stream shim failed";
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    if (v) {
      PyObject* s = PyObject_Str(v);
      if (s) {
        const char* u = PyUnicode_AsUTF8(s);
        if (u) snprintf(msg, sizeof msg, "%.*s", 150, u);
        Py_DECREF(s);
      }
    }
    PyErr_Clear();
    Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
    it.m->stream_errors++;
    native_error(c, it.cid, 2001 /* EINTERNAL */, msg);
    return;
  }
  if (r == Py_None) {
    // escalated: the shim completed (or will complete) the RPC through
    // the classic Python send path (async methods, error shapes,
    // compressed/device responses)
    Py_DECREF(r);
    it.m->stream_opens++;
    return;
  }
  PyObject* resp = r;
  PyObject* grant = nullptr;
  if (PyTuple_Check(r) && PyTuple_GET_SIZE(r) == 2) {
    resp = PyTuple_GET_ITEM(r, 0);
    grant = PyTuple_GET_ITEM(r, 1);
    if (grant == Py_None) grant = nullptr;
  }
  Py_buffer rb = {}, gb = {};
  if (PyObject_GetBuffer(resp, &rb, PyBUF_SIMPLE) != 0
      || (grant && PyObject_GetBuffer(grant, &gb, PyBUF_SIMPLE) != 0)) {
    PyErr_Clear();
    if (rb.obj) PyBuffer_Release(&rb);
    Py_DECREF(r);
    it.m->stream_errors++;
    native_error(c, it.cid, 2001, "stream shim returned non-bytes");
    return;
  }
  // response meta: cid + (domain-exchange answer) + grant TLVs — the
  // classic path orders its meta the same way for escalations
  std::string extra;
  if (it.dom_len && !lp->eng->domain_tlv.empty())
    extra.append(lp->eng->domain_tlv);
  if (gb.obj) extra.append((const char*)gb.buf, (size_t)gb.len);
  native_append_head(lp->eng, c->native_out, it.cid, 0, (size_t)rb.len,
                     extra.empty() ? nullptr : &extra);
  dp_copy(lp, DP_SERIALIZE, (size_t)rb.len);
  if (rb.len) c->native_out.append((const char*)rb.buf, rb.len);
  PyBuffer_Release(&rb);
  if (gb.obj) PyBuffer_Release(&gb);
  Py_DECREF(r);
  it.m->stream_opens++;
}

// Run a burst's worth of batched items (kind-2 raw, kind-3 slim,
// kind-4 slim-HTTP) under ONE GIL acquisition and append their
// responses to c->native_out (shipped by the burst-end native_flush as
// one writev).  This is the amortized GIL crossing of the reference's
// message-batch pattern (input_messenger.cpp:374-394: one bthread per
// batch + flush): a pipelined client pays one Python entry per read
// burst, not one per message.  Telemetry stages captured per item:
// queue (frame parse -> this batch entry), shim (item dispatch time),
// resid (parse -> response build done).
static void flush_py_batch(Loop* lp, Conn* c,
                           std::vector<PyRawItem>& batch,
                           std::vector<StreamItem>& sbatch) {
  if (batch.empty() && sbatch.empty()) return;
  int64_t t_entry = now_ns();
  if (!batch.empty()) lp->tel.burst.add((uint64_t)batch.size());
  PyGILState_STATE gs = PyGILState_Ensure();
  flush_decrefs_locked_gil(lp);
  for (PyRawItem& it : batch) {
    int lane = it.hroute ? LANE_HTTP
                         : (it.stream_id ? LANE_STREAM
                            : (it.m->kind == 3 ? LANE_SLIM : LANE_RAW));
    lp->tel.queue[lane].add(
        (uint64_t)((t_entry - it.t_parse) / 1000));
    int64_t t0 = now_ns();
    if (it.hroute)
      http_slim_item(lp, c, it);   // kind-4 slim-HTTP item
    else if (it.stream_id)
      stream_open_item(lp, c, it); // kind-5 stream-open item
    else
      raw_slim_item(lp, c, it);    // kind-2/3 tpu_std item
    int64_t t1 = now_ns();
    lp->tel.shim[lane].add((uint64_t)((t1 - t0) / 1000));
    lp->tel.resid[lane].add((uint64_t)((t1 - it.t_parse) / 1000));
  }
  if (!sbatch.empty()) {
    // kind-5 chunk delivery: EVERY stream chunk of this read burst —
    // across all streams on the connection — enters Python in this
    // ONE call (the kind-3/4 batching discipline applied to streams)
    lp->tel.stream_burst.add((uint64_t)sbatch.size());
    if (lp->eng->stream_chunks != nullptr) {
      PyObject* list = PyList_New((Py_ssize_t)sbatch.size());
      if (list) {
        bool ok = true;
        for (size_t i = 0; ok && i < sbatch.size(); i++) {
          StreamItem& si = sbatch[i];
          PyObject* t = Py_BuildValue(
              "(Kiy#)", (unsigned long long)si.sid, si.flags,
              si.payload, (Py_ssize_t)si.len);
          if (!t) { ok = false; break; }
          PyList_SET_ITEM(list, (Py_ssize_t)i, t);
        }
        if (ok) {
          PyObject* r = PyObject_CallFunctionObjArgs(
              lp->eng->stream_chunks, list, nullptr);
          if (!r)
            PyErr_WriteUnraisable(lp->eng->stream_chunks);
          else
            Py_DECREF(r);
        } else {
          PyErr_Clear();
        }
        Py_DECREF(list);
      } else {
        PyErr_Clear();
      }
    }
    sbatch.clear();
  }
  if (lp->eng->burst_end != nullptr) {
    // per-burst accounting epilogue (one call per batched GIL entry)
    PyObject* r = PyObject_CallNoArgs(lp->eng->burst_end);
    if (!r)
      PyErr_WriteUnraisable(lp->eng->burst_end);
    else
      Py_DECREF(r);
  }
  PyGILState_Release(gs);
  batch.clear();
}

// Try to answer one complete TRPC frame natively.  body = meta+payload
// (body_len bytes), meta_size from the frame header.  True = handled,
// response appended to c->native_out.  Every False exit increments a
// reason-coded fallback counter on the owning loop — the classic path
// a frame takes instead is never silent.
static bool native_try_handle(EngineImpl* eng, Loop* lp, Conn* c,
                              const char* body, size_t body_len,
                              uint32_t meta_size,
                              std::vector<PyRawItem>* batch = nullptr) {
  if (!eng->native_dispatch.load(std::memory_order_relaxed)) {
    lp->tel.fallbacks[FB_RPC_DISPATCH_OFF]++;
    return false;
  }
  MetaScan s;
  if (!scan_request_meta(body, meta_size, &s)) {
    lp->tel.fallbacks[FB_RPC_META_TAG]++;
    return false;
  }
  if (s.shm) {
    lp->tel.fallbacks[FB_RPC_SHM_LANE]++;
    return false;
  }
  if (s.compressed) {
    // compressed frames always decline (only the classic path can
    // decompress); a compressed stream OPEN earns its kind-5 name
    if (s.stream_id) {
      lp->tel.sfallbacks[SFB_COMPRESSED]++;
      NativeMethod* m0 = find_native(eng, s);
      if (m0) m0->fb_stream_open++;
    } else {
      lp->tel.fallbacks[FB_RPC_META_TAG]++;
    }
    return false;
  }
  NativeMethod* m = find_native(eng, s);
  if (s.stream_id) {
    // kind-5 STREAM OPEN: the unary call negotiating a stream rides
    // the stream shim (interceptor-chain binding).  Every decline is
    // NAMED (closed StreamFb enum); the classic Python lane serves
    // declined opens byte-identically.
    int mode = eng->stream_mode.load(std::memory_order_relaxed);
    int fb = -1;
    if (eng->lame_duck.load(std::memory_order_relaxed) >= 1)
      fb = SFB_DRAIN;         // classic path owns the ELAMEDUCK shape
    else if (mode != 1 || m == nullptr
             || m->stream_handler == nullptr)
      fb = mode == 2 ? SFB_NON_INLINE : SFB_NO_SHIM;
    else if (!batch)
      fb = SFB_CHUNK_OVERSIZE;  // direct-read path: too big to batch
    else if (s.att > kSlimAttCap) {
      lp->tel.fallbacks[FB_RPC_ATT_OVER_CAP]++;
      m->fb_att_over_cap++;
      return false;
    }
    if (fb >= 0) {
      lp->tel.sfallbacks[fb]++;
      if (m) m->fb_stream_open++;
      return false;
    }
    const char* spayload = body + meta_size;
    size_t splen = body_len - meta_size;
    if (s.att > splen) {
      m->stream_errors++;
      native_error(c, s.cid, 1003 /* EREQUEST */,
                   "attachment size exceeds body");
      return true;
    }
    PyRawItem si{};
    si.m = m;
    si.cid = s.cid;
    si.payload = spayload;
    si.plen = splen;
    si.att = s.att;
    si.dom = s.dom;
    si.dom_len = s.dom_len;
    si.conn = s.conn;
    si.conn_len = s.conn_len;
    si.trace_id = s.trace_id;
    si.span_id = s.span_id;
    si.parent_id = s.parent_id;
    si.timeout_ms = s.timeout_ms;
    si.timeout_present = s.timeout_present;
    si.ten = s.ten;
    si.ten_len = s.ten_len;
    si.stream_id = s.stream_id;       // selects the kind-5 lane
    si.stream_window = s.stream_window;
    si.t_parse = now_ns();
    batch->push_back(si);
    return true;
  }
  if (s.stream_window) {
    // window TLV without a stream id: malformed handshake — classic
    // path arbitrates (the pre-stream-lane behavior for tag 14)
    lp->tel.fallbacks[FB_RPC_META_TAG]++;
    return false;
  }
  if (!m) {
    lp->tel.fallbacks[FB_RPC_NO_METHOD]++;
    return false;
  }
  if (s.trace_id && m->kind != 3) {
    // explicit trace on an echo/const/raw method: a span must record,
    // and only the Python path has the span machinery for those lanes
    // (kind 3 carries the context through the shim instead)
    lp->tel.fallbacks[FB_RPC_TRACE_RAW]++;
    m->fb_trace_raw++;
    return false;
  }
  const char* payload = body + meta_size;
  size_t plen = body_len - meta_size;
  if (s.att > plen) {
    m->errors++;
    native_error(c, s.cid, 1003 /* EREQUEST */,
                 "attachment size exceeds body");
    return true;
  }
  PyRawItem pi{};
  pi.m = m;
  pi.cid = s.cid;
  pi.payload = payload;
  pi.plen = plen;
  pi.att = s.att;
  switch (m->kind) {
    case 0:  // echo: payload + attachment unchanged
      native_respond(c, s.cid, payload, plen, s.att);
      break;
    case 1:  // const: fixed payload, no attachment
      native_respond(c, s.cid, m->const_data.data(), m->const_data.size(),
                     0);
      break;
    case 2:  // Python raw handler: batch for one GIL entry per burst
      if (!batch) {               // direct-read path: full Python route
        lp->tel.fallbacks[FB_RPC_LARGE_FRAME]++;
        m->fb_large_frame++;
        return false;
      }
      pi.t_parse = now_ns();
      batch->push_back(pi);
      break;
    case 3:  // slim full-method dispatch: batched like kind 2; over-
             // threshold attachments take the byte-identical Python
             // route (large frames already fall back via direct read)
      if (!batch) {               // direct-read path: full Python route
        lp->tel.fallbacks[FB_RPC_LARGE_FRAME]++;
        m->fb_large_frame++;
        return false;
      }
      if (s.att > kSlimAttCap) {
        lp->tel.fallbacks[FB_RPC_ATT_OVER_CAP]++;
        m->fb_att_over_cap++;
        return false;
      }
      pi.dom = s.dom;
      pi.dom_len = s.dom_len;
      pi.conn = s.conn;
      pi.conn_len = s.conn_len;
      pi.trace_id = s.trace_id;
      pi.span_id = s.span_id;
      pi.parent_id = s.parent_id;
      pi.timeout_ms = s.timeout_ms;
      pi.timeout_present = s.timeout_present;
      pi.ten = s.ten;
      pi.ten_len = s.ten_len;
      pi.t_parse = now_ns();
      batch->push_back(pi);
      break;
    default:
      return false;
  }
  if (m->kind < 2) m->count++;   // kinds 2/3 count at batch flush
  return true;
}

// Stage accumulated native responses: MOVE native_out into the write
// queue as ONE owned WriteItem (no copy), optionally appending a
// follow-up item UNDER THE SAME LOCK — a concurrent Engine_send from a
// GIL-holding thread (stream writes, ack flushes) must never interleave
// its frames between a response's header and its zero-copy body.  No
// flush here: splitting header and body into two writevs wakes the
// blocked peer twice, and on a shared core the first wake costs a
// ~0.5ms scheduler round trip before the body is even written.
static bool native_stage(Conn* c, WriteItem* follow = nullptr) {
  std::string* s = nullptr;
  if (!c->native_out.empty()) {
    s = new (std::nothrow) std::string(std::move(c->native_out));
    if (!s) return false;
    c->native_out.clear();           // moved-from: make state definite
  }
  std::lock_guard<std::mutex> g(c->wmu);
  if (s) {
    WriteItem it;
    memset(&it.view, 0, sizeof(it.view));
    it.view.buf = (void*)s->data();
    it.view.len = (Py_ssize_t)s->size();
    it.owned_str = s;
    c->wq.push_back(it);
  }
  if (follow) c->wq.push_back(*follow);
  return true;
}

// stage + flush: the burst-end path.  False = fatal, destroy conn.
static bool native_flush(Loop* lp, Conn* c) {
  if (c->native_out.empty()) return true;
  if (!native_stage(c)) return false;
  return conn_flush(lp, c);
}

// ---------------------------------------------------------------------------
// HTTP/1.x cutting — the native engine's multi-protocol ingestion step
// (≈ the reference routing every protocol through one C++ cut loop,
// input_messenger.cpp:329).  The engine only CUTS a complete message
// (request line + headers + body, Content-Length or chunked); header
// parsing and dispatch stay in Python (protocol/http.py +
// server/http_dispatch.py) via EV_HTTP.
// ---------------------------------------------------------------------------

constexpr size_t kMaxHttpHeader = 64 * 1024;

// does the buffer start like an HTTP/1.x message?  avail>=4 guaranteed.
static bool http_sniff(const char* p) {
  static const char* kStarts[] = {"GET ",  "POST", "PUT ", "DELE",
                                  "HEAD", "OPTI", "PATC", "CONN",
                                  "TRAC", "HTTP"};
  for (const char* s : kStarts)
    if (memcmp(p, s, 4) == 0) return true;
  return false;
}

// case-insensitive search for a header NAME at line starts inside the
// header block [p, p+len); returns pointer past "name:" or nullptr
static const char* http_find_header(const char* p, size_t len,
                                    const char* name, size_t name_len) {
  const char* end = p + len;
  const char* line = p;
  while (line < end) {
    const char* eol = (const char*)memchr(line, '\n', end - line);
    size_t ll = eol ? (size_t)(eol - line) : (size_t)(end - line);
    if (ll > name_len && line[name_len] == ':'
        && strncasecmp(line, name, name_len) == 0)
      return line + name_len + 1;
    if (!eol) break;
    line = eol + 1;
  }
  return nullptr;
}

// does the header VALUE starting at v (runs to end of line within the
// block ending at blk_end) contain the token, case-insensitively?
static bool http_value_contains(const char* v, const char* blk_end,
                                const char* token, size_t token_len) {
  const char* eol = (const char*)memchr(v, '\n', blk_end - v);
  size_t vlen = (eol ? (size_t)(eol - v) : (size_t)(blk_end - v));
  if (vlen < token_len) return false;
  for (size_t i = 0; i + token_len <= vlen; i++)
    if (strncasecmp(v + i, token, token_len) == 0) return true;
  return false;
}

// walk a chunked body starting at p (first chunk-size line).
// returns consumed length through the terminal CRLF after trailers,
// 0 = need more bytes, -1 = malformed
static ssize_t http_walk_chunks(const char* p, size_t avail) {
  size_t off = 0;
  for (;;) {
    const char* nl = (const char*)memchr(p + off, '\n', avail - off);
    if (!nl) return avail - off > 32 ? -1 : 0;   // size line is short
    size_t line_end = (size_t)(nl - p);
    char* endp = nullptr;
    long sz = strtol(p + off, &endp, 16);
    if (endp == p + off || sz < 0) return -1;
    off = line_end + 1;
    if (sz == 0) {
      // trailers: zero or more header lines, then a blank line
      for (;;) {
        if (off >= avail) return 0;
        const char* tnl = (const char*)memchr(p + off, '\n',
                                              avail - off);
        if (!tnl) return 0;
        size_t tl = (size_t)(tnl - p) - off;
        off = (size_t)(tnl - p) + 1;
        if (tl == 0 || (tl == 1 && p[off - 2] == '\r'))
          return (ssize_t)off;                   // blank line: done
      }
    }
    if (off + (size_t)sz + 2 > avail) return 0;
    off += (size_t)sz;
    if (p[off] != '\r' || p[off + 1] != '\n') return -1;
    off += 2;
  }
}

// try to cut one complete HTTP message at p.  Returns total length,
// 0 = need more bytes, -1 = not/never HTTP or malformed (close),
// -2 = Content-Length body too large for the inbuf: *cl_total carries
// the full message size for the direct-read path,
// -3 = body exceeds max_body (answer 413, then close),
// -4 = incomplete chunked body about to outgrow the inbuf: switch to
// the incremental chunk-stream mode (bounded by max_body, not the
// inbuf).  *hlen_out carries the header-block length (request line
// through the blank line) whenever the headers are complete.
static ssize_t http_cut(const char* p, size_t avail, size_t max_body,
                        size_t* cl_total, size_t* hlen_out) {
  if (!http_sniff(p)) return -1;
  size_t cap = avail < kMaxHttpHeader ? avail : kMaxHttpHeader;
  const char* he = nullptr;
  for (size_t i = 3; i + 1 <= cap; i++) {
    if (p[i] == '\n' && p[i - 1] == '\r' && p[i - 2] == '\n'
        && p[i - 3] == '\r') {
      he = p + i + 1;
      break;
    }
  }
  if (!he) return avail >= kMaxHttpHeader ? -1 : 0;
  size_t hlen = (size_t)(he - p);
  *hlen_out = hlen;
  const char* te = http_find_header(p, hlen, "transfer-encoding", 17);
  if (te != nullptr && http_value_contains(te, he, "chunked", 7)) {
    // chunked framing (any other Transfer-Encoding value keeps CL
    // framing below, matching protocol/http.py's '"chunked" in te')
    ssize_t consumed = http_walk_chunks(he, avail - hlen);
    if (consumed < 0) return -1;
    if (consumed == 0) {
      // total unknown up front: once the accumulating message would
      // outgrow the inbuf, hand it to the incremental chunk FSM
      // (ADVICE r5 #4 — parity with the Python transport's
      // chunked-up-to-max_body acceptance)
      return avail + kMaxHttpHeader >= kInbufCap ? -4 : 0;
    }
    if ((size_t)consumed > max_body) return -3;
    return (ssize_t)(hlen + (size_t)consumed);
  }
  const char* cl = http_find_header(p, hlen, "content-length", 14);
  long clen = 0;
  if (cl != nullptr) {
    char* endp = nullptr;
    clen = strtol(cl, &endp, 10);
    if (endp == cl || clen < 0) return -1;
    // reject from the HEADERS, before buffering a byte of body — an
    // oversized Content-Length must not pin a giant NativeBuf and eat
    // the upload (Python's parse enforces the same max_body limit)
    if ((size_t)clen > max_body) return -3;
  }
  size_t total = hlen + (size_t)clen;
  if (avail >= total) return (ssize_t)total;   // fully buffered: deliver
  if (total > kInbufCap / 2) {
    *cl_total = total;                         // switch to direct read
    return -2;
  }
  return 0;
}

static const char k413[] =
    "HTTP/1.1 413 Payload Too Large\r\n"
    "Content-Length: 0\r\nConnection: close\r\n\r\n";

// does the (complete) request line carry the HTTP-version marker?  A
// 4-byte method-token prefix is not proof of HTTP (redis "GET k\r\n"
// collides) — only " HTTP/1." commits the conn to the HTTP cutter.
static bool line_has_http_marker(const char* p, size_t len) {
  if (len < 8) return false;
  for (size_t i = 0; i + 8 <= len; i++)
    if (memcmp(p + i, " HTTP/1.", 8) == 0) return true;
  return false;
}

// bounds for the sniff commitment: a request line longer than this, or
// one that stalls incomplete past the time budget, is arbitrated by
// the passthrough registry instead of held by the HTTP cutter forever
constexpr size_t kMaxHttpReqLine = 8 * 1024;
constexpr int64_t kSniffBudgetMs = 2000;

// Feed bytes to the incremental chunked-body FSM (mirror of
// http_walk_chunks — keep the two in sync).  Consumes from [d, d+len)
// and reports via *used how many bytes belong to THIS message.
// Returns 1 = message complete (*used ends one past the terminal LF),
// 0 = need more bytes (*used == len), -1 = malformed.
static int chunk_feed(ChunkState* cs, const char* d, size_t len,
                      size_t* used) {
  size_t off = 0;
  while (off < len) {
    char ch = d[off];
    switch (cs->phase) {
      case 0:  // chunk-size line (hex + optional extensions).  Only a
               // bounded prefix is STORED (the hex size lives at line
               // start); longer extension tails are counted and
               // skipped, matching http_walk_chunks accepting complete
               // size lines of any length.
        off++;
        if (ch == '\n') {
          size_t stored = cs->line < sizeof cs->szline - 1
                              ? cs->line : sizeof cs->szline - 1;
          cs->szline[stored] = '\0';
          char* endp = nullptr;
          long sz = strtol(cs->szline, &endp, 16);
          // reject when nothing parsed, or when the stored prefix was
          // truncated AND is hex to the brim (the size itself may have
          // been cut — an absurd >32-digit size either way)
          if (endp == cs->szline || sz < 0
              || (cs->line > stored && *endp == '\0')) {
            *used = off;
            return -1;
          }
          cs->line = 0;
          if (sz == 0) {
            cs->phase = 4;           // trailers until a blank line
            cs->first = 0;
          } else {
            cs->remaining = (size_t)sz;
            cs->phase = 1;
          }
        } else {
          if (cs->line < sizeof cs->szline - 1)
            cs->szline[cs->line] = ch;
          cs->line++;
        }
        break;
      case 1: {  // chunk data
        size_t take = len - off;
        if (take > cs->remaining) take = cs->remaining;
        cs->remaining -= take;
        off += take;
        if (cs->remaining == 0) cs->phase = 2;
        break;
      }
      case 2:  // CR after chunk data
        if (ch != '\r') { *used = off; return -1; }
        off++;
        cs->phase = 3;
        break;
      case 3:  // LF after chunk data
        if (ch != '\n') { *used = off; return -1; }
        off++;
        cs->phase = 0;
        break;
      case 4:  // trailer lines; blank line ends the message
        if (cs->line == 0) cs->first = ch;
        cs->line++;
        off++;
        if (ch == '\n') {
          size_t tl = cs->line - 1;              // excludes the LF
          cs->line = 0;
          if (tl == 0 || (tl == 1 && cs->first == '\r')) {
            *used = off;
            return 1;                            // terminal blank line
          }
        }
        break;
    }
  }
  *used = len;
  return 0;
}

// mirror of protocol/http.py STATUS_REASONS — the slim lane's native
// status line must be byte-identical with build_response's
static const char* http_reason(long status) {
  switch (status) {
    case 200: return "OK";
    case 204: return "No Content";
    case 301: return "Moved Permanently";
    case 302: return "Found";
    case 400: return "Bad Request";
    case 403: return "Forbidden";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 500: return "Internal Server Error";
    case 501: return "Not Implemented";
    case 503: return "Service Unavailable";
    default:  return "Unknown";
  }
}

// Serialize one slim-lane response natively: status line +
// Content-Length + the shim's pre-formatted header block ("Name: v\r\n"
// per line, Content-Type first) + blank line + body — the exact byte
// layout of protocol/http.py build_response(keep_alive=True).
static void http_slim_respond(Conn* c, long status, const char* hdr,
                              size_t hlen, const char* body,
                              size_t blen) {
  char line[96];
  int n = snprintf(line, sizeof line,
                   "HTTP/1.1 %ld %s\r\nContent-Length: %zu\r\n", status,
                   http_reason(status), blen);
  c->native_out.append(line, (size_t)n);
  c->native_out.append(hdr, hlen);
  c->native_out.append("\r\n", 2);
  if (blen) {
    dp_copy(c->loop, DP_SERIALIZE, blen);
    c->native_out.append(body, blen);
  }
}

// never-happens lane failure (shim raised / returned a bad shape):
// answer a plain 500 so the keep-alive conn is not desynced
static void http_slim_error(Conn* c, const char* text) {
  size_t tl = strlen(text);
  http_slim_respond(c, 500, "Content-Type: text/plain\r\n", 26, text,
                    tl);
}

// Scan one complete, fully-buffered HTTP message for slim-lane
// eligibility: HTTP/1.1, CRLF line endings, a registered METHOD+path
// route, no Transfer-Encoding / Expect / Upgrade, Connection absent or
// exactly keep-alive.  Fills the kind-4 PyRawItem fields (pointers
// into the inbuf — batch lifetime rules apply).  False = take the
// classic EV_HTTP path; every reject increments a reason-coded
// fallback counter (and the per-route breakdown once the route is
// resolved — the route lookup precedes the header walk).
static bool http_slim_match(EngineImpl* eng, Loop* lp, const char* p,
                            size_t total, size_t hlen, PyRawItem* out) {
  if (eng->lame_duck.load(std::memory_order_relaxed) >= 2) {
    // drain: the classic EV_HTTP lane owns every response now, so the
    // x-lame-duck / Connection: close headers (and the keep-alive
    // teardown they imply) come from ONE serializer
    lp->tel.fallbacks[FB_HTTP_LAME_DUCK]++;
    return false;
  }
  const char* he = p + hlen;                    // body start
  const char* nl = (const char*)memchr(p, '\n', hlen);
  if (!nl) {
    lp->tel.fallbacks[FB_HTTP_MALFORMED_LINE]++;
    return false;
  }
  const char* sp1 = (const char*)memchr(p, ' ', (size_t)(nl - p));
  if (!sp1) {
    lp->tel.fallbacks[FB_HTTP_MALFORMED_LINE]++;
    return false;
  }
  const char* sp2 =
      (const char*)memchr(sp1 + 1, ' ', (size_t)(nl - sp1 - 1));
  if (!sp2) {
    lp->tel.fallbacks[FB_HTTP_MALFORMED_LINE]++;
    return false;
  }
  // version token must be exactly "HTTP/1.1" with a CRLF line ending
  if ((size_t)(nl - sp2) != 10 || memcmp(sp2 + 1, "HTTP/1.1\r", 9) != 0) {
    lp->tel.fallbacks[FB_HTTP_VERSION]++;
    return false;
  }
  const char* tgt = sp1 + 1;
  size_t tlen = (size_t)(sp2 - tgt);
  const char* qm = (const char*)memchr(tgt, '?', tlen);
  size_t path_len = qm ? (size_t)(qm - tgt) : tlen;
  std::string key;                // "METHOD\0path" — SSO for short ones
  key.reserve((size_t)(sp1 - p) + 1 + path_len);
  key.append(p, (size_t)(sp1 - p));
  key.push_back('\0');
  key.append(tgt, path_len);
  auto itr = eng->http_routes.find(key);
  if (itr == eng->http_routes.end()) {
    lp->tel.fallbacks[FB_HTTP_NO_ROUTE]++;
    return false;
  }
  HttpRoute* route = itr->second;
  // reject helper: global reason + the resolved route's breakdown
  auto route_fb = [&](FbReason fb, RouteFb rfb) {
    lp->tel.fallbacks[fb]++;
    route->fb[rfb]++;
    return false;
  };
  const char* ctype = nullptr;
  uint32_t ctlen = 0;
  const char* attsz = nullptr;
  uint32_t attszlen = 0;
  const char* tp = nullptr;
  uint32_t tplen = 0;
  const char* dl = nullptr;
  uint32_t dllen = 0;
  const char* xt = nullptr;
  uint32_t xtlen = 0;
  const char* line = nl + 1;
  while (line < he) {
    const char* leol =
        (const char*)memchr(line, '\n', (size_t)(he - line));
    if (!leol) break;
    size_t ll = (size_t)(leol - line);          // excl LF
    if (ll == 0 || line[ll - 1] != '\r')        // demand CRLF
      return route_fb(FB_HTTP_BAD_HEADER, RFB_BAD_HEADER);
    ll--;                                       // excl CR
    if (ll == 0) break;                         // blank line: done
    const char* col = (const char*)memchr(line, ':', ll);
    if (!col) return route_fb(FB_HTTP_BAD_HEADER, RFB_BAD_HEADER);
    size_t nlen = (size_t)(col - line);
    const char* v = col + 1;
    size_t vlen = ll - nlen - 1;
    switch (nlen) {
      case 6:
        if (strncasecmp(line, "expect", 6) == 0)
          return route_fb(FB_HTTP_EXPECT, RFB_EXPECT);
        break;
      case 7:
        if (strncasecmp(line, "upgrade", 7) == 0)
          return route_fb(FB_HTTP_UPGRADE, RFB_UPGRADE);
        break;
      case 8:
        if (strncasecmp(line, "x-tenant", 8) == 0) {
          xt = v;                               // tenant identity —
          xtlen = (uint32_t)vlen;               // the shim's admission
        }                                       // stage keys off it
        break;
      case 10:
        if (strncasecmp(line, "connection", 10) == 0) {
          while (vlen && (*v == ' ' || *v == '\t')) { v++; vlen--; }
          while (vlen && (v[vlen - 1] == ' ' || v[vlen - 1] == '\t'))
            vlen--;
          if (vlen != 10 || strncasecmp(v, "keep-alive", 10) != 0)
            return route_fb(FB_HTTP_CONNECTION,  // close / upgrade /
                            RFB_CONNECTION);     // odd value
        }
        break;
      case 11:
        if (strncasecmp(line, "traceparent", 11) == 0) {
          tp = v;                               // W3C trace context —
          tplen = (uint32_t)vlen;               // the shim parses it,
        }                                       // traced stays slim
        break;
      case 13:
        if (strncasecmp(line, "x-deadline-ms", 13) == 0) {
          dl = v;                               // remaining deadline —
          dllen = (uint32_t)vlen;               // the shim sheds
        }                                       // queue-expired requests
        break;
      case 12:
        if (strncasecmp(line, "content-type", 12) == 0) {
          ctype = v;                            // last one wins, like
          ctlen = (uint32_t)vlen;               // HttpHeaders.set
        }
        break;
      case 17:
        if (strncasecmp(line, "transfer-encoding", 17) == 0)
          return route_fb(FB_HTTP_TRANSFER_ENCODING,  // chunked OR
                          RFB_TE);                    // identity
        break;
      case 21:
        if (strncasecmp(line, "x-rpc-attachment-size", 21) == 0) {
          attsz = v;
          attszlen = (uint32_t)vlen;
        }
        break;
    }
    line = leol + 1;
  }
  out->hroute = route;
  out->payload = he;
  out->plen = total - hlen;
  out->query = qm ? qm + 1 : nullptr;
  out->qlen = qm ? (uint32_t)(tlen - path_len - 1) : 0;
  out->ctype = ctype;
  out->ctlen = ctlen;
  out->attsz = attsz;
  out->attszlen = attszlen;
  out->tp = tp;
  out->tplen = tplen;
  out->dl = dl;
  out->dllen = dllen;
  out->xt = xt;
  out->xtlen = xtlen;
  return true;
}

// parse as many complete frames as possible from c->inbuf / direct reads
static bool parse_frames_inner(EngineImpl* eng, Loop* lp, Conn* c,
                               std::vector<PyRawItem>& batch,
                               std::vector<StreamItem>& sbatch) {
  if (c->passthrough) {
    // deliver the whole gulp; Python's registry owns this connection
    size_t avail = c->in_end - c->in_start;
    if (avail == 0) return true;
    bool ok;
    {
      PyGILState_STATE gs = PyGILState_Ensure();
      flush_decrefs_locked_gil(lp);
      NativeBuf* b = nativebuf_new((Py_ssize_t)avail);
      ok = (b != nullptr);
      if (ok) {
        dp_copy(lp, DP_INGEST, avail);
        memcpy(b->data, c->inbuf + c->in_start, avail);
        PyObject* r = PyObject_CallFunction(
            eng->dispatch, "iKNl", EV_BYTES,
            (unsigned long long)c->id, (PyObject*)b, 0L);
        if (!r) PyErr_WriteUnraisable(eng->dispatch);
        else Py_DECREF(r);
      }
      PyGILState_Release(gs);
    }
    c->in_start = c->in_end = 0;
    return ok;
  }
  if (c->chunk) {
    // mid chunked-stream HTTP message (ADVICE r5 #4): feed new bytes
    // through the chunk FSM; raw bytes accumulate until the terminal
    // blank line, then ONE EV_HTTP delivers the whole message.  Burst
    // batches are empty here — the mode consumes everything until the
    // message completes.  The raw stream is buffered once here and
    // copied once into the delivery NativeBuf (total size is unknown
    // until the terminal chunk, so the CL direct-read pattern does not
    // apply); the Python-transport chunked path pays the same
    // fetch-then-decode double buffering, so parity holds.
    size_t avail = c->in_end - c->in_start;
    if (avail == 0) return true;
    const char* p = c->inbuf + c->in_start;
    size_t used = 0;
    int st = chunk_feed(c->chunk, p, avail, &used);
    c->chunk->acc.append(p, used);
    c->in_start += used;
    if (c->in_start == c->in_end) c->in_start = c->in_end = 0;
    if (st < 0) return false;               // malformed chunk framing
    if (c->chunk->acc.size() > c->chunk->cap) {
      // raw stream outgrew http_max_body (the Python parser's too_big
      // bound): clean 413, then close
      c->native_out.append(k413, sizeof(k413) - 1);
      native_flush(lp, c);
      return false;
    }
    if (st == 0) return true;               // need more bytes
    // slim responses accumulated earlier in this burst (before the -4
    // entry) must reach the wire before Python can answer this
    // message — HTTP responses have no correlation id
    if (!c->native_out.empty() && !native_flush(lp, c)) return false;
    bool ok;
    {
      PyGILState_STATE gs = PyGILState_Ensure();
      flush_decrefs_locked_gil(lp);
      NativeBuf* b = nativebuf_new((Py_ssize_t)c->chunk->acc.size());
      ok = (b != nullptr);
      if (ok) {
        dp_copy(lp, DP_INGEST, c->chunk->acc.size());
        memcpy(b->data, c->chunk->acc.data(), c->chunk->acc.size());
        PyObject* r = PyObject_CallFunction(
            eng->dispatch, "iKNl", EV_HTTP, (unsigned long long)c->id,
            (PyObject*)b, 0L);
        if (!r) PyErr_WriteUnraisable(eng->dispatch);
        else Py_DECREF(r);
      }
      PyGILState_Release(gs);
    }
    count_msg(eng, lp, c);
    delete c->chunk;
    c->chunk = nullptr;
    if (!ok) return false;
    // fall through: pipelined bytes after the chunked message parse on
  }
  for (;;) {
    size_t avail = c->in_end - c->in_start;
    const char* p = c->inbuf + c->in_start;
    if (avail < 4) return true;
    uint32_t body = 0, meta = 0;
    int kind;
    uint32_t hdr;
    if (memcmp(p, "TRPC", 4) == 0) {
      if (avail < kHeaderSize) return true;
      memcpy(&body, p + 4, 4);
      memcpy(&meta, p + 8, 4);
      if (body > kMaxBody || meta > body) return false;
      kind = EV_MESSAGE;
      hdr = kHeaderSize;
    } else if (memcmp(p, "TICI", 4) == 0) {
      if (avail < kAckHeader) return true;
      uint32_t count = 0;
      memcpy(&count, p + 4, 4);
      if (count > (1u << 20)) return false;
      body = count * 8;
      meta = count;
      kind = EV_ACK;
      hdr = kAckHeader;
    } else if (memcmp(p, "TSTR", 4) == 0) {
      // stream frame: [magic][u8 flags][u64 dest][u32 len][payload].
      // Frames for a kind-5 NATIVE stream are consumed here: credit
      // feedback settles entirely in C++ (zero GIL entries), DATA and
      // CLOSE chunks batch with the burst and enter Python ONCE in
      // flush_py_batch.  Everything else (pure-Python streams, closed
      // streams, forged ids, oversize chunks) rides the classic
      // EV_STREAM path under a NAMED StreamFb reason.
      if (avail < 17) return true;
      uint32_t len = 0;
      memcpy(&len, p + 13, 4);
      if (len > kMaxBody) return false;
      size_t stotal = 17 + (size_t)len;
      if (eng->nstreams.load(std::memory_order_acquire) != 0) {
        uint64_t dest = 0;
        memcpy(&dest, p + 5, 8);
        std::shared_ptr<NativeStream> ns;
        {
          std::lock_guard<std::mutex> g(eng->smu);
          auto sit = eng->streams.find(dest);
          if (sit != eng->streams.end()) ns = sit->second;
        }
        if (ns && ns->conn_id == c->id) {
          if (avail >= stotal) {
            uint8_t flags = (uint8_t)p[4];
            if (flags == 1 /* F_FEEDBACK */) {
              if (len >= 8) {
                uint64_t consumed = 0;
                memcpy(&consumed, p + 17, 8);
                std::lock_guard<std::mutex> g(ns->mu);
                // clamp to produced: an over-acking peer must not
                // push remote_consumed past produced, or the unsigned
                // produced - remote_consumed window check underflows
                // and stalls the stream forever (the Python lane's
                // signed arithmetic tolerates over-ack; so do we)
                if (consumed > ns->produced) consumed = ns->produced;
                if (consumed > ns->remote_consumed) {
                  ns->remote_consumed = consumed;
                  ns->cv.notify_all();   // wake blocked producers
                }
              }
              lp->tel.stream_feedbacks++;
            } else {
              if (flags == 2 || flags == 3) {  // F_CLOSE / F_RST
                std::lock_guard<std::mutex> g(ns->mu);
                ns->closed = true;       // writers fail fast, not at
                ns->cv.notify_all();     // their credit timeout
              }
              sbatch.push_back(StreamItem{
                  dest, (int)flags, p + 17, (size_t)len});
              lp->tel.stream_chunks_in++;
            }
            c->in_start += stotal;
            count_msg(eng, lp, c);
            continue;
          }
          if (stotal > kInbufCap / 2) {
            // about to switch to the direct-read path: too large to
            // batch — the Python streaming lane delivers it whole
            // (counted ONCE: the switch below consumes the frame)
            lp->tel.sfallbacks[SFB_CHUNK_OVERSIZE]++;
          }
          // incomplete small frame: generic tail waits for more bytes
        } else if (avail >= stotal) {
          // not ours (pure-Python stream, closed, or forged onto the
          // wrong conn): the classic dispatch path arbitrates
          lp->tel.sfallbacks[SFB_UNREGISTERED]++;
        }
      } else if (avail >= stotal) {
        lp->tel.sfallbacks[
            eng->stream_mode.load(std::memory_order_relaxed) == 0
                ? SFB_NO_SHIM : SFB_UNREGISTERED]++;
      }
      body = 13 + len;
      meta = 0;
      kind = EV_STREAM;
      hdr = 4;
    } else {
      // not a natively-framed protocol.  HTTP/1.x is cut natively and
      // handed to Python whole (EV_HTTP); anything else that isn't
      // even HTTP-shaped flips the connection to PASSTHROUGH — the
      // Python protocol registry (h2/gRPC, redis, thrift, streams)
      // cuts and dispatches it, so the native port speaks every
      // protocol the Python transport does.  Malformed HTTP (sniffed
      // as HTTP but uncuttable) stays a close.
      if (!http_sniff(p)) {
        flush_py_batch(lp, c, batch, sbatch);
        if (!c->native_out.empty() && !native_flush(lp, c)) return false;
        c->passthrough = true;
        // re-enter: the passthrough head delivers the buffered bytes
        return parse_frames_inner(eng, lp, c, batch, sbatch);
      }
      if (c->http_state == 0) {
        // SNIFF COMMITMENT (ADVICE r5 #5): a 4-byte method-token match
        // is not proof of HTTP.  Only a request line carrying
        // " HTTP/1." commits the conn to the HTTP cutter; a complete
        // line without it (or an over-long / time-stalled one, swept
        // by the loop) goes to the passthrough registry instead of
        // hanging here waiting for a CRLFCRLF that never comes.
        size_t linecap = avail < kMaxHttpReqLine ? avail
                                                 : kMaxHttpReqLine;
        const char* nl = (const char*)memchr(p, '\n', linecap);
        bool commit = false, arbitrate = false;
        if (nl) {
          if (line_has_http_marker(p, (size_t)(nl - p))) commit = true;
          else arbitrate = true;
        } else if (avail >= kMaxHttpReqLine) {
          arbitrate = true;
        }
        if (arbitrate) {
          flush_py_batch(lp, c, batch, sbatch);
          if (!c->native_out.empty() && !native_flush(lp, c))
            return false;
          c->sniff_deadline = 0;
          c->passthrough = true;
          return parse_frames_inner(eng, lp, c, batch, sbatch);
        }
        if (!commit) {
          // incomplete request line: wait, but only within the sniff
          // budget — the loop's sweep flips a stalled conn to the
          // passthrough registry (a slow legit HTTP client is still
          // served there: the registry speaks HTTP too)
          if (c->sniff_deadline == 0) {
            c->sniff_deadline = now_ms() + kSniffBudgetMs;
            lp->sniffing.push_back(c->id);
          }
          if (c->in_start > 0) {
            flush_py_batch(lp, c, batch, sbatch);
            memmove(c->inbuf, c->inbuf + c->in_start, avail);
            c->in_end = avail;
            c->in_start = 0;
          }
          return true;
        }
        c->http_state = 1;
        c->sniff_deadline = 0;
      }
      size_t cl_total = 0, http_hlen = 0;
      ssize_t hr = http_cut(
          p, avail, eng->http_max_body.load(std::memory_order_relaxed),
          &cl_total, &http_hlen);
      if (hr == -3) {
        // body over the limit: answer 413 cleanly, then close
        flush_py_batch(lp, c, batch, sbatch);
        c->native_out.append(k413, sizeof(k413) - 1);
        native_flush(lp, c);
        return false;
      }
      if (hr == -4) {
        // chunked body outgrowing the inbuf: stream raw bytes through
        // the incremental chunk FSM, bounded by http_max_body
        lp->tel.fallbacks[FB_HTTP_CHUNK_STREAM]++;
        flush_py_batch(lp, c, batch, sbatch);
        c->chunk = new (std::nothrow) ChunkState();
        if (!c->chunk) return false;
        c->chunk->cap =
            http_hlen
            + eng->http_max_body.load(std::memory_order_relaxed);
        size_t used = 0;
        int st = chunk_feed(c->chunk, p + http_hlen, avail - http_hlen,
                            &used);
        (void)used;                    // all buffered bytes are ours
        c->chunk->acc.assign(p, avail);
        c->in_start = c->in_end = 0;
        if (st < 0) return false;
        // st == 1 cannot happen (http_walk_chunks said incomplete);
        // more bytes arrive through the chunk head above
        return true;
      }
      if (hr > 0) {
        if (eng->http_slim.load(std::memory_order_relaxed)) {
          // SLIM HTTP LANE (kind 4): eligible messages batch with the
          // burst and enter Python once, in flush_py_batch
          PyRawItem hit{};
          if (http_slim_match(eng, lp, p, (size_t)hr, http_hlen,
                              &hit)) {
            hit.t_parse = now_ns();
            c->in_start += (size_t)hr;
            count_msg(eng, lp, c);
            batch.push_back(hit);
            continue;
          }
        } else {
          lp->tel.fallbacks[FB_HTTP_SLIM_OFF]++;
        }
        // one complete HTTP message: classic EV_HTTP dispatch
        flush_py_batch(lp, c, batch, sbatch);   // wire order vs earlier frames
        if (!c->native_out.empty() && !native_flush(lp, c)) return false;
        c->in_start += (size_t)hr;
        count_msg(eng, lp, c);
        bool ok;
        {
          PyGILState_STATE gs = PyGILState_Ensure();
          flush_decrefs_locked_gil(lp);
          NativeBuf* b = nativebuf_new((Py_ssize_t)hr);
          ok = (b != nullptr);
          if (ok) {
            dp_copy(lp, DP_INGEST, (size_t)hr);
            memcpy(b->data, p, (size_t)hr);
            PyObject* r = PyObject_CallFunction(
                eng->dispatch, "iKNl", EV_HTTP,
                (unsigned long long)c->id, (PyObject*)b, 0L);
            if (!r) PyErr_WriteUnraisable(eng->dispatch);
            else Py_DECREF(r);
          }
          PyGILState_Release(gs);
        }
        if (!ok) return false;
        continue;
      }
      if (hr == 0) {
        // incomplete HTTP message: wait for more bytes
        if (c->in_start > 0) {
          flush_py_batch(lp, c, batch, sbatch);
          memmove(c->inbuf, c->inbuf + c->in_start, avail);
          c->in_end = avail;
          c->in_start = 0;
        }
        return true;
      }
      if (hr == -2) {
        // large Content-Length body: direct-into-buffer reads, same
        // machinery as large tpu_std frames (msg_kind = EV_HTTP)
        lp->tel.fallbacks[FB_HTTP_LARGE_BODY]++;
        flush_py_batch(lp, c, batch, sbatch);
        NativeBuf* b;
        {
          PyGILState_STATE gs = PyGILState_Ensure();
          flush_decrefs_locked_gil(lp);
          b = nativebuf_new((Py_ssize_t)cl_total);
          PyGILState_Release(gs);
        }
        if (!b) return false;
        dp_copy(lp, DP_INGEST_SPILL, avail);
        memcpy(b->data, p, avail);
        c->msg = b;
        c->msg_filled = avail;
        c->msg_meta = 0;
        c->msg_kind = EV_HTTP;
        c->in_start = c->in_end = 0;
        return true;
      }
      // hr == -1: hand the readable prefix to Python, then die
      NativeBuf* b;
      {
        PyGILState_STATE gs = PyGILState_Ensure();
        b = nativebuf_new((Py_ssize_t)avail);
        if (b) memcpy(b->data, p, avail);
        PyGILState_Release(gs);
      }
      if (b) call_dispatch(eng, lp, EV_UNKNOWN, c->id, (PyObject*)b, 0);
      return false;
    }
    size_t total = hdr + (size_t)body;
    if (avail >= total) {
      c->in_start += total;
      count_msg(eng, lp, c);
      // native dispatch first: echo-class frames never leave C++ (the
      // response rides c->native_out, coalesced across the burst);
      // kind=2 Python raw handlers are BATCHED into one GIL entry
      if (kind == EV_MESSAGE
          && native_try_handle(eng, lp, c, p + hdr, body, meta, &batch)) {
        continue;
      }
      // a Python-path frame mid-burst: flush queued native responses
      // first so wire order matches arrival order
      if (!c->native_out.empty() && !native_flush(lp, c)) return false;
      // whole frame in the buffer: ONE GIL acquisition covers the
      // NativeBuf alloc+copy and the Python dispatch (two round trips
      // here doubled the GIL-convoy exposure per message)
      bool ok;
      {
        PyGILState_STATE gs = PyGILState_Ensure();
        flush_decrefs_locked_gil(lp);
        NativeBuf* b = nativebuf_new((Py_ssize_t)body);
        ok = (b != nullptr);
        if (ok) {
          dp_copy(lp, DP_INGEST, (size_t)body);
          memcpy(b->data, p + hdr, body);
          PyObject* r = PyObject_CallFunction(
              eng->dispatch, "iKNl", kind, (unsigned long long)c->id,
              (PyObject*)b, (long)meta);
          if (!r) PyErr_WriteUnraisable(eng->dispatch);
          else Py_DECREF(r);
        }
        PyGILState_Release(gs);
      }
      if (!ok) return false;
      continue;
    }
    // incomplete: large bodies switch to direct-into-buffer reads
    if (total > kInbufCap / 2) {
      NativeBuf* b;
      {
        PyGILState_STATE gs = PyGILState_Ensure();
        // drain deferred view releases NOW: on the pure-native path
        // this is the loop's only periodic GIL point, and the previous
        // large request's buffer must reach the freelist before this
        // alloc or every request pays a fresh multi-MB mmap + soft
        // faults (measured 2x throughput loss at 1MB)
        flush_decrefs_locked_gil(lp);
        b = nativebuf_new((Py_ssize_t)body);
        PyGILState_Release(gs);
      }
      if (!b) return false;
      size_t have = avail - hdr;
      dp_copy(lp, DP_INGEST_SPILL, have);
      memcpy(b->data, p + hdr, have);
      c->msg = b;
      c->msg_filled = have;
      c->msg_meta = meta;
      c->msg_kind = kind;
      // inbuf fully consumed
      c->in_start = c->in_end = 0;
      return true;
    }
    // small frame, wait for more bytes; compact if consumed prefix is big
    if (c->in_start > 0) {
      // batched kind=2 items point into the consumed prefix this
      // memmove is about to overwrite — run them first
      flush_py_batch(lp, c, batch, sbatch);
      memmove(c->inbuf, c->inbuf + c->in_start, avail);
      c->in_end = avail;
      c->in_start = 0;
    }
    return true;
  }
}

static bool parse_frames(EngineImpl* eng, Loop* lp, Conn* c) {
  std::vector<PyRawItem> batch;
  std::vector<StreamItem> sbatch;
  bool ok = parse_frames_inner(eng, lp, c, batch, sbatch);
  // requests already complete on the wire get processed even when a
  // later frame kills the connection (same order the Python path gives)
  flush_py_batch(lp, c, batch, sbatch);
  if (!ok && !c->native_out.empty()) {
    // the conn is about to be destroyed, but the batch above ran side
    // effects (user code, MethodStatus) for requests that were fully
    // on the wire — deliver their responses best-effort before the
    // close, like the classic path's inline sends reached the socket
    // before a close
    native_flush(lp, c);
  }
  return ok;
}

static bool conn_readable(EngineImpl* eng, Loop* lp, Conn* c) {
  for (;;) {
    if (c->msg) {
      // direct read of the in-flight message body
      size_t want = (size_t)c->msg->size - c->msg_filled;
      ssize_t r = recv(c->fd, c->msg->data + c->msg_filled, want, 0);
      if (r == 0) {
        // peer half-closed mid-burst: deliver responses already
        // produced for earlier pipelined requests best-effort
        if (!c->native_out.empty()) native_flush(lp, c);
        return false;
      }
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK)
          return native_flush(lp, c);       // burst over: ship responses
        if (errno == EINTR) continue;
        return false;
      }
      eng->bytes_in += (uint64_t)r;
      c->msg_filled += (size_t)r;
      if (c->msg_filled == (size_t)c->msg->size) {
        NativeBuf* b = c->msg;
        c->msg = nullptr;
        c->msg_filled = 0;
        count_msg(eng, lp, c);
        // native echo on the large-frame path: respond zero-copy out of
        // the received NativeBuf (header+meta owned; body is a view)
        MetaScan s;
        NativeMethod* m = nullptr;
        if (c->msg_kind == EV_MESSAGE) {
          // reason-coded mirror of native_try_handle's screening for
          // the direct-read (large-frame) path
          if (!eng->native_dispatch.load(std::memory_order_relaxed))
            lp->tel.fallbacks[FB_RPC_DISPATCH_OFF]++;
          else if (!scan_request_meta(b->data, c->msg_meta, &s))
            lp->tel.fallbacks[FB_RPC_META_TAG]++;
          else if (s.shm)
            lp->tel.fallbacks[FB_RPC_SHM_LANE]++;
          else if (s.stream_id) {
            // large-frame stream open: reason-coded mirror of
            // native_try_handle's kind-5 screening — same request,
            // same NAME regardless of frame size (only the
            // genuinely-eligible-but-oversize shape earns
            // stream_chunk_oversize)
            NativeMethod* m0 = find_native(eng, s);
            int mode = eng->stream_mode.load(std::memory_order_relaxed);
            int sfb;
            if (s.compressed)            // same rank order as the
              sfb = SFB_COMPRESSED;      // buffered-path screening
            else if (eng->lame_duck.load(std::memory_order_relaxed) >= 1)
              sfb = SFB_DRAIN;
            else if (mode != 1 || m0 == nullptr
                     || m0->stream_handler == nullptr)
              sfb = mode == 2 ? SFB_NON_INLINE : SFB_NO_SHIM;
            else
              sfb = SFB_CHUNK_OVERSIZE;
            lp->tel.sfallbacks[sfb]++;
            if (m0) m0->fb_stream_open++;
          } else if (s.compressed || s.stream_window)
            lp->tel.fallbacks[FB_RPC_META_TAG]++;
          else if ((m = find_native(eng, s)) == nullptr)
            lp->tel.fallbacks[FB_RPC_NO_METHOD]++;
        }
        if (m && (m->kind == 2 || m->kind == 3)) {
          lp->tel.fallbacks[FB_RPC_LARGE_FRAME]++;
          m->fb_large_frame++;
          m = nullptr;   // large-frame Python raw/slim: the bridge's
                         // zero-copy NativeBuf path beats a batch copy
                         // (for slim this IS the big-attachment
                         // fallback to the classic dispatch)
        }
        if (m && s.trace_id) {
          // traced echo/const on the direct-read path: the span must
          // record — mirror of native_try_handle's trace screening
          lp->tel.fallbacks[FB_RPC_TRACE_RAW]++;
          m->fb_trace_raw++;
          m = nullptr;
        }
        if (m) {
          size_t plen = (size_t)b->size - c->msg_meta;
          if (s.att > plen) {
            m->errors++;
            native_error(c, s.cid, 1003, "attachment size exceeds body");
            PyGILState_STATE gs = PyGILState_Ensure();
            Py_DECREF(b);
            PyGILState_Release(gs);
          } else if (m->kind == 1) {
            native_respond(c, s.cid, m->const_data.data(),
                           m->const_data.size(), 0);
            m->count++;
            PyGILState_STATE gs = PyGILState_Ensure();
            Py_DECREF(b);
            PyGILState_Release(gs);
          } else {
            // echo: append header+meta to native_out, then queue the
            // received buffer itself (offset past the request meta) —
            // the megabyte body is never copied
            native_append_head(eng, c->native_out, s.cid, s.att, plen);
            WriteItem it;
            bool got = false;
            {
              PyGILState_STATE gs = PyGILState_Ensure();
              flush_decrefs_locked_gil(lp);
              got = PyObject_GetBuffer((PyObject*)b, &it.view,
                                       PyBUF_SIMPLE) == 0;
              Py_DECREF(b);   // the view (if any) holds its own ref
              PyGILState_Release(gs);
            }
            if (!got) return false;
            it.offset = c->msg_meta;   // skip the request meta bytes
            // stage header+meta and the body view ATOMICALLY (one wmu
            // hold — no foreign frame can land between them), flush
            // once: a single writev, a single peer wakeup
            if (!native_stage(c, &it)) {
              PyGILState_STATE gs = PyGILState_Ensure();
              PyBuffer_Release(&it.view);
              PyGILState_Release(gs);
              return false;
            }
            if (!conn_flush(lp, c)) return false;
            m->count++;
          }
          continue;
        }
        if (!c->native_out.empty() && !native_flush(lp, c)) return false;
        call_dispatch(eng, lp, c->msg_kind, c->id, (PyObject*)b,
                      (long)c->msg_meta);
      }
      continue;
    }
    // buffered read into the fixed inbuf (compact first if needed)
    if (c->in_end + 65536 > kInbufCap && c->in_start > 0) {
      memmove(c->inbuf, c->inbuf + c->in_start, c->in_end - c->in_start);
      c->in_end -= c->in_start;
      c->in_start = 0;
    }
    size_t room = kInbufCap - c->in_end;
    if (room > 65536) room = 65536;
    ssize_t r = recv(c->fd, c->inbuf + c->in_end, room, 0);
    if (r <= 0) {
      if (r == 0) {
        if (!c->native_out.empty()) native_flush(lp, c);
        return false;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK)
        return native_flush(lp, c);         // burst over: ship responses
      if (errno == EINTR) continue;
      return false;
    }
    c->in_end += (size_t)r;
    eng->bytes_in += (uint64_t)r;
    if (c->in_end > lp->tel.inbuf_hwm) lp->tel.inbuf_hwm = c->in_end;
    if (!parse_frames(eng, lp, c)) return false;
  }
}

static void accept_conns(EngineImpl* eng, Loop* lp) {
  // SHARDED ACCEPT (SO_REUSEPORT): each loop accepts off its OWN
  // listen fd and pins the conn to itself for life — no rr handoff, no
  // adopt round trip, no cross-loop state on the whole read→shim→writev
  // path (brpc's one-EventDispatcher-per-core discipline).  The shared
  // single-fd path (lp->listen_fd < 0 — platforms/configs without
  // REUSEPORT) keeps the round-robin + adopt-eventfd placement.
  int lfd = lp->listen_fd >= 0 ? lp->listen_fd : eng->listen_fd;
  for (;;) {
    struct sockaddr_in addr;
    socklen_t alen = sizeof(addr);
    int fd = accept4(lfd, (struct sockaddr*)&addr, &alen,
                     SOCK_NONBLOCK);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    Conn* c = new Conn();
    c->fd = fd;
    c->inbuf = (char*)malloc(kInbufCap);
    c->id = eng->next_conn++;
    char ip[64] = {0};
    inet_ntop(AF_INET, &addr.sin_addr, ip, sizeof(ip));
    c->peer_ip = ip;
    c->peer_port = ntohs(addr.sin_port);
    // placement: own-listener accepts pin to the accepting loop;
    // shared-fd accepts assign round-robin (fallback path)
    Loop* target = lp->listen_fd >= 0
        ? lp : eng->loops[eng->rr++ % eng->loops.size()];
    c->loop = target;
    {
      std::lock_guard<std::mutex> g(eng->cmu);
      eng->by_id[c->id] = c;
    }
    // EV_OPEN MUST be dispatched before the fd reaches any epoll: once a
    // loop can read the first frame, EV_MESSAGE may race ahead of the
    // bridge learning the connection and the request would be dropped.
    {
      PyGILState_STATE gs = PyGILState_Ensure();
      flush_decrefs_locked_gil(lp);
      PyObject* r =
          PyObject_CallFunction(eng->dispatch, "iKsl", EV_OPEN,
                                (unsigned long long)c->id, ip,
                                (long)c->peer_port);
      if (!r)
        PyErr_WriteUnraisable(eng->dispatch);
      else
        Py_DECREF(r);
      PyGILState_Release(gs);
    }
    if (target == lp) {
      lp->tel.accepts++;
      lp->conns[c->id] = c;
      struct epoll_event ev;
      ev.events = EPOLLIN;
      ev.data.u64 = c->id;
      epoll_ctl(lp->epfd, EPOLL_CTL_ADD, fd, &ev);
    } else {
      loop_post(target, c->id, HO_ADOPT);
    }
  }
}

static thread_local Loop* t_current_loop = nullptr;

static void loop_run(Loop* lp) {
  t_current_loop = lp;
  EngineImpl* eng = lp->eng;
  struct epoll_event evs[128];
  while (!eng->stopping.load()) {
    // busy/idle split: time blocked in epoll_wait is idle, everything
    // else in the iteration (callbacks, parsing, writes) is busy —
    // the loop-thread analogue of /hotspots for the C++ data plane.
    // With engine_busy_poll_us set, the loop first SPINS on zero-
    // timeout polls for that long: events harvested in the spin skip
    // the sleep/wake scheduler round trip (the latency-tail knob; the
    // spin window is accounted idle — spinning is waiting, not work).
    int64_t t_pre = now_ns();
    int n = 0;
    int spin_us = eng->busy_poll_us.load(std::memory_order_relaxed);
    if (spin_us > 0) {
      int64_t spin_end = t_pre + (int64_t)spin_us * 1000;
      do {
        n = epoll_wait(lp->epfd, evs, 128, 0);
      } while (n == 0 && now_ns() < spin_end && !eng->stopping.load());
      if (n > 0) lp->tel.spin_polls++;
    }
    if (n == 0) n = epoll_wait(lp->epfd, evs, 128, 200);
    int64_t t_wake = now_ns();
    lp->tel.idle_ns += (uint64_t)(t_wake - t_pre);
    lp->tel.polls++;
    struct BusyScope {
      LoopTelemetry* tel;
      int64_t t0;
      ~BusyScope() { tel->busy_ns += (uint64_t)(now_ns() - t0); }
    } busy_scope{&lp->tel, t_wake};
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    // cross-loop handoff drain: take the whole MPSC stack in ONE
    // acquire exchange (no lock), reverse it for FIFO processing, and
    // run each node — flush requests from completion threads, close
    // requests, rr-fallback adopts.  Producers never block; this loop
    // never locks: the per-core lanes share nothing on the hot path.
    {
      HandoffNode* head =
          lp->handoff_head.exchange(nullptr, std::memory_order_acquire);
      HandoffNode* rev = nullptr;
      while (head) {
        HandoffNode* nx = head->next;
        head->next = rev;
        rev = head;
        head = nx;
      }
      while (rev) {
        HandoffNode* node = rev;
        rev = rev->next;
        lp->tel.handoffs++;
        uint64_t id = node->id;
        int op = node->op;
        delete node;
        if (op == HO_ADOPT) {            // adopt a freshly accepted conn
          Conn* c = nullptr;
          {
            std::lock_guard<std::mutex> g(eng->cmu);
            auto it = eng->by_id.find(id);
            if (it != eng->by_id.end()) c = it->second;
          }
          if (c) {
            lp->tel.accepts++;
            lp->conns[id] = c;
            struct epoll_event ev;
            ev.events = EPOLLIN;
            ev.data.u64 = id;
            epoll_ctl(lp->epfd, EPOLL_CTL_ADD, c->fd, &ev);
          }
          continue;
        }
        if (op == HO_FLUSH) {
          auto it = lp->conns.find(id);
          if (it != lp->conns.end()) {
            // reset BEFORE flushing: a send racing in after this sees
            // queued bytes and posts a fresh node
            it->second->flush_queued.store(false,
                                           std::memory_order_release);
            if (!conn_flush(lp, it->second))
              conn_destroy(eng, lp, it->second, true);
          }
          continue;
        }
        // HO_CLOSE
        auto it = lp->conns.find(id);
        if (it == lp->conns.end()) continue;
        Conn* c = it->second;
        if (c->closing) continue;        // already lingering
        // close-after-flush: drain what the kernel will take now; if
        // the queue still holds bytes (short writev / EAGAIN — exactly
        // the Connection: close responses this path serves), keep the
        // conn EPOLLOUT-armed and destroy when the queue empties,
        // bounded by a linger deadline.  conn_flush returns false once
        // a closing conn is fully drained (or on a fatal error).
        c->closing = true;
        if (!conn_flush(lp, c)) {
          conn_destroy(eng, lp, c, true);
          continue;
        }
        c->close_deadline = now_ms() + kCloseLingerMs;
        lp->lingering.push_back(id);
        struct epoll_event ev;
        ev.events = EPOLLOUT;            // stop reading; write-drain only
        ev.data.u64 = id;
        epoll_ctl(lp->epfd, EPOLL_CTL_MOD, c->fd, &ev);
      }
    }
    for (int i = 0; i < n; i++) {
      uint64_t id = evs[i].data.u64;
      if (id == 0) {  // wakefd or listener
        if (evs[i].data.u64 == 0) {
          uint64_t drain;
          while (read(lp->wakefd, &drain, 8) > 0) {
          }
        }
        continue;
      }
      if (id == UINT64_MAX) {  // listener
        accept_conns(eng, lp);
        continue;
      }
      auto it = lp->conns.find(id);
      if (it == lp->conns.end()) continue;
      Conn* c = it->second;
      bool ok = true;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) ok = false;
      if (ok && (evs[i].events & EPOLLOUT)) ok = conn_flush(lp, c);
      if (ok && (evs[i].events & EPOLLIN) && !c->closing)
        ok = conn_readable(eng, lp, c);
      if (!ok) conn_destroy(eng, lp, c, true);
    }
    // sniff sweep: conns holding a sniffed-HTTP prefix that never
    // committed (" HTTP/1." unseen) within the budget are flipped to
    // the passthrough registry — a slow legit HTTP client is still
    // served there, and a colliding protocol gets arbitrated instead
    // of hanging against the CRLFCRLF hunt (ADVICE r5 #5)
    if (!lp->sniffing.empty()) {
      int64_t now = now_ms();
      std::vector<uint64_t> keep;
      for (uint64_t id : lp->sniffing) {
        auto it = lp->conns.find(id);
        if (it == lp->conns.end()) continue;          // conn gone
        Conn* c = it->second;
        if (c->sniff_deadline == 0) continue;         // committed
        if (now < c->sniff_deadline) {
          keep.push_back(id);
          continue;
        }
        c->sniff_deadline = 0;
        c->passthrough = true;
        if (!parse_frames(eng, lp, c)) conn_destroy(eng, lp, c, true);
      }
      lp->sniffing.swap(keep);
    }
    // linger sweep: closing conns that could not drain within the
    // deadline are torn down (destroyed conns are simply absent)
    if (!lp->lingering.empty()) {
      int64_t now = now_ms();
      std::vector<uint64_t> keep;
      for (uint64_t id : lp->lingering) {
        auto it = lp->conns.find(id);
        if (it == lp->conns.end()) continue;
        Conn* c = it->second;
        if (now >= c->close_deadline)
          conn_destroy(eng, lp, c, true);
        else
          keep.push_back(id);
      }
      lp->lingering.swap(keep);
    }
  }
  // teardown: close all conns owned by this loop, then drain any
  // handoff nodes posted after the last iteration (an un-adopted conn
  // must still be destroyed — its fd is open and it is in by_id)
  std::vector<Conn*> cs;
  for (auto& kv : lp->conns) cs.push_back(kv.second);
  for (Conn* c : cs) conn_destroy(eng, lp, c, false);
  HandoffNode* head =
      lp->handoff_head.exchange(nullptr, std::memory_order_acquire);
  while (head) {
    HandoffNode* nx = head->next;
    if (head->op == HO_ADOPT) {
      Conn* c = nullptr;
      {
        std::lock_guard<std::mutex> g(eng->cmu);
        auto it = eng->by_id.find(head->id);
        if (it != eng->by_id.end()) c = it->second;
      }
      if (c) conn_destroy(eng, lp, c, false);
    }
    delete head;
    head = nx;
  }
}

// ---------------------------------------------------------------------------
// Python object wrapping EngineImpl
// ---------------------------------------------------------------------------

typedef struct {
  PyObject_HEAD EngineImpl* eng;
} EngineObj;

static PyObject* Engine_new(PyTypeObject* type, PyObject* args,
                            PyObject* kwds) {
  PyObject* dispatch;
  int nloops = 1;
  int external = 0;
  static const char* kwlist[] = {"dispatch", "loops", "external_loops",
                                 nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "O|ip", (char**)kwlist,
                                   &dispatch, &nloops, &external))
    return nullptr;
  if (!PyCallable_Check(dispatch)) {
    PyErr_SetString(PyExc_TypeError, "dispatch must be callable");
    return nullptr;
  }
  if (nloops < 1) nloops = 1;
  if (nloops > 16) nloops = 16;
  EngineObj* self = (EngineObj*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->eng = new EngineImpl();
  self->eng->external_loops = external != 0;
  Py_INCREF(dispatch);
  self->eng->dispatch = dispatch;
  for (int i = 0; i < nloops; i++) {
    Loop* lp = new Loop();
    lp->eng = self->eng;
    lp->index = i;
    lp->epfd = epoll_create1(EPOLL_CLOEXEC);
    lp->wakefd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.u64 = 0;  // wake marker
    epoll_ctl(lp->epfd, EPOLL_CTL_ADD, lp->wakefd, &ev);
    self->eng->loops.push_back(lp);
  }
  return (PyObject*)self;
}

static PyObject* Engine_listen(EngineObj* self, PyObject* args) {
  int fd;
  if (!PyArg_ParseTuple(args, "i", &fd)) return nullptr;
  EngineImpl* eng = self->eng;
  eng->listen_fd = fd;
  // listener lives on loop 0 with the UINT64_MAX marker
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX;
  if (epoll_ctl(eng->loops[0]->epfd, EPOLL_CTL_ADD, fd, &ev) != 0) {
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  // start threads on first listen (external mode: the bridge runs the
  // loops on Python threads via run_loop — see EngineImpl comment)
  eng->started = true;
  if (!eng->external_loops) {
    for (Loop* lp : eng->loops) {
      if (!lp->thr.joinable()) lp->thr = std::thread(loop_run, lp);
    }
  }
  Py_RETURN_NONE;
}

// listen_sharded(fds) — the SO_REUSEPORT sharded-accept path: exactly
// one bound+listening fd per loop; each loop accepts its own
// connections and pins them to itself for life (no rr handoff, no
// adopt round trip).  The single-fd listen() above remains the
// fallback for platforms/configs without REUSEPORT.
static PyObject* Engine_listen_sharded(EngineObj* self, PyObject* args) {
  PyObject* fds;
  if (!PyArg_ParseTuple(args, "O", &fds)) return nullptr;
  EngineImpl* eng = self->eng;
  PyObject* seq = PySequence_Fast(fds, "fds must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if ((size_t)n != eng->loops.size()) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError,
                    "listen_sharded needs exactly one fd per loop");
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    long fd = PyLong_AsLong(PySequence_Fast_GET_ITEM(seq, i));
    if (fd == -1 && PyErr_Occurred()) {
      Py_DECREF(seq);
      return nullptr;
    }
    Loop* lp = eng->loops[(size_t)i];
    lp->listen_fd = (int)fd;
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.u64 = UINT64_MAX;
    if (epoll_ctl(lp->epfd, EPOLL_CTL_ADD, (int)fd, &ev) != 0) {
      Py_DECREF(seq);
      PyErr_SetFromErrno(PyExc_OSError);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  eng->started = true;
  if (!eng->external_loops) {
    for (Loop* lp : eng->loops) {
      if (!lp->thr.joinable()) lp->thr = std::thread(loop_run, lp);
    }
  }
  Py_RETURN_NONE;
}

// set_lame_duck(on) — operability plane: enter/leave drain mode.
// While on: natively-built tpu_std responses carry the lame-duck TLV,
// new kind-4 slim-HTTP matches decline to the classic lane, and every
// listener is DISARMED from its loop's epoll — accepting stops but the
// fds stay open+bound, so a hot-restart successor can inherit them
// (SCM_RIGHTS) with the kernel listen queue intact.  off re-arms.
static PyObject* Engine_set_lame_duck(EngineObj* self, PyObject* args) {
  int mode;   // 0 = off, 1 = accept pause only, 2 = pause + signal
  if (!PyArg_ParseTuple(args, "i", &mode)) return nullptr;
  if (mode < 0) mode = 0;
  if (mode > 2) mode = 2;
  EngineImpl* eng = self->eng;
  int on = mode != 0;
  int prev = eng->lame_duck.exchange(mode, std::memory_order_relaxed);
  if ((prev != 0) == on) Py_RETURN_NONE;   // arm state unchanged
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX;
  if (eng->listen_fd >= 0 && !eng->loops.empty()) {
    if (on)
      epoll_ctl(eng->loops[0]->epfd, EPOLL_CTL_DEL, eng->listen_fd,
                nullptr);
    else
      epoll_ctl(eng->loops[0]->epfd, EPOLL_CTL_ADD, eng->listen_fd, &ev);
  }
  for (Loop* lp : eng->loops) {
    if (lp->listen_fd < 0) continue;
    if (on)
      epoll_ctl(lp->epfd, EPOLL_CTL_DEL, lp->listen_fd, nullptr);
    else
      epoll_ctl(lp->epfd, EPOLL_CTL_ADD, lp->listen_fd, &ev);
  }
  Py_RETURN_NONE;
}

// listener_fds() — the bound+listening fds this engine accepts on
// (shard listeners included): the hot-restart exporter passes them to
// the successor binary over a unix socket.
static PyObject* Engine_listener_fds(EngineObj* self, PyObject* args) {
  (void)args;
  EngineImpl* eng = self->eng;
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  if (eng->listen_fd >= 0) {
    PyObject* v = PyLong_FromLong(eng->listen_fd);
    if (!v || PyList_Append(out, v) != 0) {
      Py_XDECREF(v);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(v);
  }
  for (Loop* lp : eng->loops) {
    if (lp->listen_fd < 0) continue;
    PyObject* v = PyLong_FromLong(lp->listen_fd);
    if (!v || PyList_Append(out, v) != 0) {
      Py_XDECREF(v);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(v);
  }
  return out;
}

// set_busy_poll_us(us) — arm/disarm the pre-epoll busy-poll spin.
// Runtime-settable (relaxed atomic): flag flips take effect on the
// next loop iteration.
static PyObject* Engine_set_busy_poll_us(EngineObj* self,
                                         PyObject* args) {
  int us;
  if (!PyArg_ParseTuple(args, "i", &us)) return nullptr;
  if (us < 0) us = 0;
  if (us > 1000000) us = 1000000;   // 1s: far past any sane spin
  self->eng->busy_poll_us.store(us, std::memory_order_relaxed);
  Py_RETURN_NONE;
}

// run_loop(index) — the body of one event loop, called from a Python
// thread in external_loops mode.  Blocks (GIL released) until stop().
// The calling thread's resident Python frames keep the datastack
// chunk mapped, so per-burst handler dispatch avoids mmap churn.
static PyObject* Engine_run_loop(EngineObj* self, PyObject* args) {
  int idx;
  if (!PyArg_ParseTuple(args, "i", &idx)) return nullptr;
  EngineImpl* eng = self->eng;
  if (idx < 0 || (size_t)idx >= eng->loops.size()) {
    PyErr_SetString(PyExc_IndexError, "loop index out of range");
    return nullptr;
  }
  Loop* lp = eng->loops[idx];
  Py_BEGIN_ALLOW_THREADS;
  loop_run(lp);
  Py_END_ALLOW_THREADS;
  Py_RETURN_NONE;
}

// register_native_method(svc, mth, kind, data=b"", handler=None) —
// pre-listen only.  kind 0 = echo (payload+attachment back unchanged),
// 1 = const(data), 2 = Python @raw_method handler called from the
// engine loop (burst-batched; one GIL entry per read burst),
// 3 = slim full-method dispatch shim (burst-batched like 2; called as
// handler(payload, att, cid, conn_id, dom, nonce, recv_ns, trace,
// timeout_ms), None return = out-of-band).
static PyObject* Engine_register_native_method(EngineObj* self,
                                               PyObject* args) {
  const char* svc;
  const char* mth;
  int kind;
  Py_buffer data = {};
  PyObject* handler = nullptr;
  if (!PyArg_ParseTuple(args, "ssi|y*O", &svc, &mth, &kind, &data,
                        &handler))
    return nullptr;
  EngineImpl* eng = self->eng;
  if (eng->started) {
    if (data.obj) PyBuffer_Release(&data);
    PyErr_SetString(PyExc_RuntimeError,
                    "native methods must be registered before listen()");
    return nullptr;
  }
  if (kind < 0 || kind > 3) {
    if (data.obj) PyBuffer_Release(&data);
    PyErr_SetString(PyExc_ValueError, "unknown native method kind");
    return nullptr;
  }
  if (kind >= 2 && (handler == nullptr || handler == Py_None
                    || !PyCallable_Check(handler))) {
    if (data.obj) PyBuffer_Release(&data);
    PyErr_SetString(PyExc_TypeError,
                    "kind 2/3 requires a callable handler");
    return nullptr;
  }
  std::string key(svc);
  key.push_back('\0');
  key.append(mth);
  auto it = eng->native_methods.find(key);
  NativeMethod* m = it != eng->native_methods.end() ? it->second
                                                    : new NativeMethod();
  m->kind = kind;
  if (data.obj) {
    m->const_data.assign((const char*)data.buf, (size_t)data.len);
    PyBuffer_Release(&data);
  } else {
    m->const_data.clear();
  }
  Py_XDECREF(m->handler);
  m->handler = nullptr;
  if (kind >= 2) {
    Py_INCREF(handler);
    m->handler = handler;
  }
  eng->native_methods[key] = m;
  Py_RETURN_NONE;
}

// set_burst_end(callable_or_None) — per-burst accounting epilogue for
// the batched shim lanes; pre-listen only (loops read it lock-free)
static PyObject* Engine_set_burst_end(EngineObj* self, PyObject* args) {
  PyObject* cb;
  if (!PyArg_ParseTuple(args, "O", &cb)) return nullptr;
  if (self->eng->started) {
    PyErr_SetString(PyExc_RuntimeError,
                    "burst_end must be set before listen()");
    return nullptr;
  }
  if (cb != Py_None && !PyCallable_Check(cb)) {
    PyErr_SetString(PyExc_TypeError, "burst_end must be callable");
    return nullptr;
  }
  Py_XDECREF(self->eng->burst_end);
  self->eng->burst_end = nullptr;
  if (cb != Py_None) {
    Py_INCREF(cb);
    self->eng->burst_end = cb;
  }
  Py_RETURN_NONE;
}

static PyObject* Engine_set_native_dispatch(EngineObj* self,
                                            PyObject* args) {
  int on;
  if (!PyArg_ParseTuple(args, "p", &on)) return nullptr;
  self->eng->native_dispatch.store(on != 0, std::memory_order_relaxed);
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// Kind-5 streaming lane: per-method stream-open shims, batched chunk
// delivery, and the WRITE side — C++-accounted credit windows with
// chunk coalescing (many streams' chunks -> one owned buffer -> one
// writev per connection).
// ---------------------------------------------------------------------------

// set_stream_shim(svc, mth, handler) — kind-5 stream-OPEN shim for an
// already-registered kind-3 method; pre-listen only.
static PyObject* Engine_set_stream_shim(EngineObj* self, PyObject* args) {
  const char* svc;
  const char* mth;
  PyObject* handler;
  if (!PyArg_ParseTuple(args, "ssO", &svc, &mth, &handler))
    return nullptr;
  EngineImpl* eng = self->eng;
  if (eng->started) {
    PyErr_SetString(PyExc_RuntimeError,
                    "stream shims must be set before listen()");
    return nullptr;
  }
  if (!PyCallable_Check(handler)) {
    PyErr_SetString(PyExc_TypeError, "stream shim must be callable");
    return nullptr;
  }
  std::string key(svc);
  key.push_back('\0');
  key.append(mth);
  auto it = eng->native_methods.find(key);
  if (it == eng->native_methods.end() || it->second->kind != 3) {
    PyErr_SetString(PyExc_ValueError,
                    "stream shim requires a registered kind-3 method");
    return nullptr;
  }
  Py_INCREF(handler);
  Py_XDECREF(it->second->stream_handler);
  it->second->stream_handler = handler;
  Py_RETURN_NONE;
}

// set_stream_chunks(callable_or_None) — the ONE batched chunk-delivery
// entry: callable(list[(sid, flags, payload_bytes)]); pre-listen only.
static PyObject* Engine_set_stream_chunks(EngineObj* self,
                                          PyObject* args) {
  PyObject* cb;
  if (!PyArg_ParseTuple(args, "O", &cb)) return nullptr;
  if (self->eng->started) {
    PyErr_SetString(PyExc_RuntimeError,
                    "stream_chunks must be set before listen()");
    return nullptr;
  }
  if (cb != Py_None && !PyCallable_Check(cb)) {
    PyErr_SetString(PyExc_TypeError, "stream_chunks must be callable");
    return nullptr;
  }
  Py_XDECREF(self->eng->stream_chunks);
  self->eng->stream_chunks = nullptr;
  if (cb != Py_None) {
    Py_INCREF(cb);
    self->eng->stream_chunks = cb;
  }
  Py_RETURN_NONE;
}

// set_stream_mode(mode) — 0 = lane off, 1 = on, 2 = declined because
// the server runs user code off the loop; names the fallback reason.
static PyObject* Engine_set_stream_mode(EngineObj* self, PyObject* args) {
  int mode;
  if (!PyArg_ParseTuple(args, "i", &mode)) return nullptr;
  if (mode < 0 || mode > 2) {
    PyErr_SetString(PyExc_ValueError, "stream mode must be 0, 1 or 2");
    return nullptr;
  }
  self->eng->stream_mode.store(mode, std::memory_order_relaxed);
  Py_RETURN_NONE;
}

// stream_register(conn_id, sid, peer_sid, window) — adopt one accepted
// stream onto the kind-5 lane.  Called by the stream-open shim (GIL
// held, ON the owning loop inside the batched entry) BEFORE the grant
// response leaves, so no peer frame can race the registration.
static PyObject* Engine_stream_register(EngineObj* self, PyObject* args) {
  unsigned long long conn_id, sid, peer_sid, window;
  if (!PyArg_ParseTuple(args, "KKKK", &conn_id, &sid, &peer_sid,
                        &window))
    return nullptr;
  EngineImpl* eng = self->eng;
  auto ns = std::make_shared<NativeStream>();
  ns->sid = sid;
  ns->peer_sid = peer_sid;
  ns->conn_id = conn_id;
  ns->window = window ? window : (2ull << 20);
  {
    std::lock_guard<std::mutex> g(eng->smu);
    eng->streams[sid] = ns;
    eng->nstreams.store(eng->streams.size(), std::memory_order_release);
  }
  Py_RETURN_NONE;
}

// stream_unregister(sid) — drop a stream from the lane (close path).
// Blocked producers wake with "closed".  Returns whether it was ours.
static PyObject* Engine_stream_unregister(EngineObj* self,
                                          PyObject* args) {
  unsigned long long sid;
  if (!PyArg_ParseTuple(args, "K", &sid)) return nullptr;
  EngineImpl* eng = self->eng;
  std::shared_ptr<NativeStream> ns;
  {
    std::lock_guard<std::mutex> g(eng->smu);
    auto it = eng->streams.find(sid);
    if (it != eng->streams.end()) {
      ns = it->second;
      eng->streams.erase(it);
      eng->nstreams.store(eng->streams.size(),
                          std::memory_order_release);
    }
  }
  if (!ns) Py_RETURN_FALSE;
  {
    std::lock_guard<std::mutex> g(ns->mu);
    ns->closed = true;
    ns->cv.notify_all();
  }
  Py_RETURN_TRUE;
}

// build one TSTR frame header (17 bytes) into out
static void stream_frame_head(std::string& out, uint8_t flags,
                              uint64_t dest, uint32_t len) {
  char h[17];
  memcpy(h, "TSTR", 4);
  h[4] = (char)flags;
  memcpy(h + 5, &dest, 8);
  memcpy(h + 13, &len, 4);
  out.append(h, 17);
}

// Reserve `len` bytes of write credit on ns, blocking (caller must NOT
// hold the GIL) until the peer's feedback frees window or timeout.
// Python-lane parity: a write is admitted while ANY credit remains —
// requiring room for the whole chunk would deadlock chunks larger
// than the window.  0 = ok, -1 = credit timeout, -2 = closed.
static int stream_reserve(EngineImpl* eng, NativeStream* ns, size_t len,
                          int timeout_ms) {
  std::unique_lock<std::mutex> g(ns->mu);
  if (ns->closed) return -2;
  if (ns->produced - ns->remote_consumed >= ns->window) {
    eng->s_credit_stalls++;
    bool ok = ns->cv.wait_for(
        g, std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 1),
        [&] {
          return ns->closed
                 || ns->produced - ns->remote_consumed < ns->window;
        });
    if (!ok) return -1;
  }
  if (ns->closed) return -2;
  ns->produced += (uint64_t)len;
  return 0;
}

// queue one owned buffer on conn_id and hand the flush to the owning
// loop (GIL must be held: it serializes this against conn_destroy's
// delete, exactly like Engine_send).  Consumes `s` either way.
static bool send_owned(EngineImpl* eng, uint64_t conn_id,
                       std::string* s) {
  Conn* c = nullptr;
  {
    std::lock_guard<std::mutex> g(eng->cmu);
    auto it = eng->by_id.find(conn_id);
    if (it != eng->by_id.end()) c = it->second;
  }
  if (!c || c->dead || c->closing) {
    delete s;
    return false;
  }
  {
    std::lock_guard<std::mutex> g(c->wmu);
    WriteItem it;
    memset(&it.view, 0, sizeof(it.view));
    it.view.buf = (void*)s->data();
    it.view.len = (Py_ssize_t)s->size();
    it.owned_str = s;
    c->wq.push_back(it);
  }
  bool expect = false;
  if (c->flush_queued.compare_exchange_strong(
          expect, true, std::memory_order_acq_rel))
    loop_post(c->loop, c->id, HO_FLUSH);
  return true;
}

// stream_write_many(items, timeout_ms=10000) -> list[int] — the burst
// write path: items is [(sid, payload), ...]; chunks are credit-
// reserved in order (GIL RELEASED across the waits — a stalled stream
// blocks only its producer thread, never a loop), framed into ONE
// owned buffer per connection and shipped as one writev.  Per-item
// status: 0 = queued, -1 = credit exhaustion (backpressure — the
// producer should yield and retry), -2 = stream closed/unknown.
static PyObject* Engine_stream_write_many(EngineObj* self,
                                          PyObject* args) {
  PyObject* items;
  int timeout_ms = 10000;
  if (!PyArg_ParseTuple(args, "O|i", &items, &timeout_ms))
    return nullptr;
  EngineImpl* eng = self->eng;
  PyObject* seq = PySequence_Fast(items, "items must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  struct Pend {
    uint64_t sid = 0;
    Py_buffer buf = {};
    int status = -2;
    std::shared_ptr<NativeStream> ns;
  };
  std::vector<Pend> pend((size_t)n);
  bool argerr = false;
  for (Py_ssize_t i = 0; i < n && !argerr; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2) {
      argerr = true;
      break;
    }
    unsigned long long sid =
        PyLong_AsUnsignedLongLong(PyTuple_GET_ITEM(item, 0));
    if (sid == (unsigned long long)-1 && PyErr_Occurred()) {
      argerr = true;
      break;
    }
    if (PyObject_GetBuffer(PyTuple_GET_ITEM(item, 1), &pend[i].buf,
                           PyBUF_SIMPLE) != 0) {
      argerr = true;
      break;
    }
    pend[i].sid = sid;
    {
      std::lock_guard<std::mutex> g(eng->smu);
      auto it = eng->streams.find(sid);
      if (it != eng->streams.end()) pend[i].ns = it->second;
    }
  }
  if (argerr) {
    for (auto& p : pend)
      if (p.buf.obj) PyBuffer_Release(&p.buf);
    Py_DECREF(seq);
    if (!PyErr_Occurred())
      PyErr_SetString(PyExc_TypeError,
                      "items must be (sid, payload) tuples");
    return nullptr;
  }
  eng->s_write_batches++;
  // credit + framing with the GIL released: the Py_buffer views stay
  // pinned by the references taken above.  timeout_ms bounds the
  // WHOLE batch, not each item: N simultaneously stalled streams must
  // cost the caller one bounded stall, not N of them (the continuous
  // batcher's one-short-stall-then-evict contract)
  std::unordered_map<uint64_t, std::string*> per_conn;
  Py_BEGIN_ALLOW_THREADS;
  auto t_end = std::chrono::steady_clock::now()
               + std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms
                                                          : 1);
  for (auto& p : pend) {
    if (!p.ns) continue;            // status stays -2
    int left_ms = (int)std::chrono::duration_cast<
        std::chrono::milliseconds>(
        t_end - std::chrono::steady_clock::now()).count();
    if (left_ms < 1) left_ms = 1;   // budget spent: fail fast, 1ms cap
    int st = stream_reserve(eng, p.ns.get(), (size_t)p.buf.len,
                            left_ms);
    p.status = st;
    if (st != 0) continue;
    std::string*& out = per_conn[p.ns->conn_id];
    if (out == nullptr) out = new std::string();
    stream_frame_head(*out, 0 /* F_DATA */, p.ns->peer_sid,
                      (uint32_t)p.buf.len);
    out->append((const char*)p.buf.buf, (size_t)p.buf.len);
    eng->s_chunks_out++;
    eng->s_chunk_bytes_out += (uint64_t)p.buf.len;
  }
  Py_END_ALLOW_THREADS;
  // a dead/closing connection drops its whole buffer: report those
  // items closed (-2), not success — the Python lane answers
  // EFAILEDSOCKET for the same state, and the decode batcher keys
  // eviction off the status
  std::unordered_set<uint64_t> dead_conns;
  for (auto& kv : per_conn)
    if (!send_owned(eng, kv.first, kv.second))
      dead_conns.insert(kv.first);
  if (!dead_conns.empty()) {
    for (auto& p : pend)
      if (p.status == 0 && p.ns
          && dead_conns.count(p.ns->conn_id) != 0)
        p.status = -2;
  }
  PyObject* out = PyList_New(n);
  bool ok = out != nullptr;
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* v = PyLong_FromLong(pend[i].status);
    if (!v) ok = false;
    else PyList_SET_ITEM(out, i, v);
  }
  for (auto& p : pend)
    if (p.buf.obj) PyBuffer_Release(&p.buf);
  Py_DECREF(seq);
  if (!ok) {
    Py_XDECREF(out);
    return nullptr;
  }
  return out;
}

// stream_write(sid, payload, timeout_ms=10000) -> int — single-chunk
// convenience over the same reserve/frame/ship path.
static PyObject* Engine_stream_write(EngineObj* self, PyObject* args) {
  unsigned long long sid;
  Py_buffer buf = {};
  int timeout_ms = 10000;
  if (!PyArg_ParseTuple(args, "Ky*|i", &sid, &buf, &timeout_ms))
    return nullptr;
  EngineImpl* eng = self->eng;
  std::shared_ptr<NativeStream> ns;
  {
    std::lock_guard<std::mutex> g(eng->smu);
    auto it = eng->streams.find(sid);
    if (it != eng->streams.end()) ns = it->second;
  }
  int st = -2;
  std::string* s = nullptr;
  if (ns) {
    Py_BEGIN_ALLOW_THREADS;
    st = stream_reserve(eng, ns.get(), (size_t)buf.len, timeout_ms);
    if (st == 0) {
      s = new (std::nothrow) std::string();
      if (s) {
        stream_frame_head(*s, 0 /* F_DATA */, ns->peer_sid,
                          (uint32_t)buf.len);
        s->append((const char*)buf.buf, (size_t)buf.len);
      } else {
        // frame alloc failed AFTER the credit reservation: roll the
        // reservation back, or the window shrinks by bytes the peer
        // can never ack (permanent spurious backpressure)
        std::lock_guard<std::mutex> g(ns->mu);
        ns->produced -= (uint64_t)buf.len;
      }
    }
    Py_END_ALLOW_THREADS;
  }
  if (s != nullptr) {
    if (send_owned(eng, ns->conn_id, s)) {
      eng->s_chunks_out++;
      eng->s_chunk_bytes_out += (uint64_t)buf.len;
    } else {
      st = -2;       // conn dead/closing: the chunk was dropped — the
    }                // Python lane's EFAILEDSOCKET shape, not success
  }
  PyBuffer_Release(&buf);
  return PyLong_FromLong(st == 0 && s == nullptr ? -2 : st);
}

// register_http_route(method, path, handler) — pre-listen only.  The
// SLIM HTTP LANE (kind 4): eligible HTTP/1.1 requests matching
// METHOD+path are parsed in C++, burst-batched, and dispatched to the
// shim as handler(body, query, content_type, att_size, conn_id,
// recv_ns, traceparent, x_deadline_ms, x_tenant); a
// (status, header_block, body) return is serialized natively, bytes
// are appended verbatim (pre-built classic escalations), None means
// the shim completed out-of-band.
static PyObject* Engine_register_http_route(EngineObj* self,
                                            PyObject* args) {
  const char* method;
  const char* path;
  PyObject* handler;
  if (!PyArg_ParseTuple(args, "ssO", &method, &path, &handler))
    return nullptr;
  EngineImpl* eng = self->eng;
  if (eng->started) {
    PyErr_SetString(PyExc_RuntimeError,
                    "http routes must be registered before listen()");
    return nullptr;
  }
  if (!PyCallable_Check(handler)) {
    PyErr_SetString(PyExc_TypeError, "handler must be callable");
    return nullptr;
  }
  std::string key(method);
  key.push_back('\0');
  key.append(path);
  auto it = eng->http_routes.find(key);
  HttpRoute* r = it != eng->http_routes.end() ? it->second
                                              : new HttpRoute();
  Py_INCREF(handler);
  Py_XDECREF(r->handler);
  r->handler = handler;
  eng->http_routes[key] = r;
  Py_RETURN_NONE;
}

static PyObject* Engine_set_http_slim(EngineObj* self, PyObject* args) {
  int on;
  if (!PyArg_ParseTuple(args, "p", &on)) return nullptr;
  self->eng->http_slim.store(on != 0, std::memory_order_relaxed);
  Py_RETURN_NONE;
}

// http_slim_stats() -> {"METHOD path": (handled, errors)}, or
// http_slim_stats(method, path) -> (handled, errors)
static PyObject* Engine_http_slim_stats(EngineObj* self, PyObject* args) {
  EngineImpl* eng = self->eng;
  const char* method = nullptr;
  const char* path = nullptr;
  if (!PyArg_ParseTuple(args, "|ss", &method, &path)) return nullptr;
  if (method != nullptr && path != nullptr) {
    std::string key(method);
    key.push_back('\0');
    key.append(path);
    auto it = eng->http_routes.find(key);
    if (it == eng->http_routes.end())
      return Py_BuildValue("(KK)", 0ULL, 0ULL);
    return Py_BuildValue("(KK)",
                         (unsigned long long)it->second->count.load(),
                         (unsigned long long)it->second->errors.load());
  }
  PyObject* d = PyDict_New();
  if (!d) return nullptr;
  for (auto& kv : eng->http_routes) {
    std::string name = kv.first;
    size_t z = name.find('\0');
    if (z != std::string::npos) name[z] = ' ';
    PyObject* t = Py_BuildValue(
        "(KK)", (unsigned long long)kv.second->count.load(),
        (unsigned long long)kv.second->errors.load());
    if (!t || PyDict_SetItemString(d, name.c_str(), t) != 0) {
      Py_XDECREF(t);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(t);
  }
  return d;
}

static PyObject* Engine_set_domain_tlv(EngineObj* self, PyObject* args) {
  Py_buffer data = {};
  if (!PyArg_ParseTuple(args, "y*", &data)) return nullptr;
  if (self->eng->started) {
    PyBuffer_Release(&data);
    PyErr_SetString(PyExc_RuntimeError,
                    "domain TLV must be set before listen()");
    return nullptr;
  }
  self->eng->domain_tlv.assign((const char*)data.buf, (size_t)data.len);
  PyBuffer_Release(&data);
  Py_RETURN_NONE;
}

static PyObject* Engine_set_http_max_body(EngineObj* self,
                                          PyObject* args) {
  unsigned long long n;
  if (!PyArg_ParseTuple(args, "K", &n)) return nullptr;
  if (n > (unsigned long long)kMaxBody) n = kMaxBody;
  self->eng->http_max_body.store((size_t)n, std::memory_order_relaxed);
  Py_RETURN_NONE;
}

// native_stats() -> {"svc.mth": (answered, errors)}, or
// native_stats(svc, mth) -> (answered, errors) — counters of natively-
// dispatched requests (they never reach Python's MethodStatus; bvar
// PassiveStatus readers surface these; the two-arg form avoids
// materializing the whole map per metric read)
static PyObject* Engine_native_stats(EngineObj* self, PyObject* args) {
  EngineImpl* eng = self->eng;
  const char* svc = nullptr;
  const char* mth = nullptr;
  if (!PyArg_ParseTuple(args, "|ss", &svc, &mth)) return nullptr;
  if (svc != nullptr && mth != nullptr) {
    std::string key(svc);
    key.push_back('\0');
    key.append(mth);
    auto it = eng->native_methods.find(key);
    if (it == eng->native_methods.end())
      return Py_BuildValue("(KK)", 0ULL, 0ULL);
    return Py_BuildValue("(KK)",
                         (unsigned long long)it->second->count.load(),
                         (unsigned long long)it->second->errors.load());
  }
  PyObject* d = PyDict_New();
  if (!d) return nullptr;
  for (auto& kv : eng->native_methods) {
    std::string name = kv.first;
    size_t z = name.find('\0');
    if (z != std::string::npos) name[z] = '.';
    PyObject* t = Py_BuildValue(
        "(KK)", (unsigned long long)kv.second->count.load(),
        (unsigned long long)kv.second->errors.load());
    if (!t || PyDict_SetItemString(d, name.c_str(), t) != 0) {
      Py_XDECREF(t);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(t);
  }
  return d;
}

// ---- telemetry snapshot helpers (GIL held) ----

static PyObject* hist_buckets(const uint64_t* b) {
  PyObject* l = PyList_New(kHistBuckets);
  if (!l) return nullptr;
  for (int i = 0; i < kHistBuckets; i++) {
    PyObject* v = PyLong_FromUnsignedLongLong(b[i]);
    if (!v) {
      Py_DECREF(l);
      return nullptr;
    }
    PyList_SET_ITEM(l, i, v);
  }
  return l;
}

static int set_u64(PyObject* d, const char* k, uint64_t v) {
  PyObject* o = PyLong_FromUnsignedLongLong(v);
  if (!o) return -1;
  int rc = PyDict_SetItemString(d, k, o);
  Py_DECREF(o);
  return rc;
}

// set "<name>": bucket list, "<name>_count", "<name>_sum" on d
static int set_hist(PyObject* d, const char* name, const Hist& h) {
  PyObject* l = hist_buckets(h.b);
  if (!l) return -1;
  int rc = PyDict_SetItemString(d, name, l);
  Py_DECREF(l);
  if (rc != 0) return -1;
  char key[64];
  snprintf(key, sizeof key, "%s_count", name);
  if (set_u64(d, key, h.count) != 0) return -1;
  snprintf(key, sizeof key, "%s_sum", name);
  return set_u64(d, key, h.sum);
}

static void hist_merge(Hist& dst, const Hist& src) {
  for (int i = 0; i < kHistBuckets; i++) dst.b[i] += src.b[i];
  dst.count += src.count;
  dst.sum += src.sum;
}

// telemetry() -> one dict with the engine's whole observability table:
// reason-coded fallback counters, per-lane stage histograms
// (queue/shim/resid, log2-us buckets), burst & writev-coalescing
// distributions, write-queue/inbuf high-water marks, per-loop
// busy/idle nanoseconds, and per-method/per-route breakdowns.  ONE
// GIL crossing serves every bvar/portal reader per sampling interval
// — replaces the per-var native_stats/http_slim_stats polling.
static PyObject* Engine_telemetry(EngineObj* self, PyObject*) {
  EngineImpl* eng = self->eng;
  // aggregate per-loop counters (racy by design: each loop's thread
  // owns its LoopTelemetry; a snapshot may trail a few increments,
  // which monotonic counters tolerate)
  uint64_t fb[FB_REASONS] = {};
  uint64_t sfb[SFB_REASONS] = {};
  Hist queue[kLanes], shim[kLanes], resid[kLanes], burst, wiov, sburst;
  uint64_t wq_hwm = 0, inbuf_hwm = 0;
  uint64_t s_chunks_in = 0, s_feedbacks = 0;
  uint64_t dp[kDpStages] = {}, dpb[kDpStages] = {};
  PyObject* loops = PyList_New((Py_ssize_t)eng->loops.size());
  if (!loops) return nullptr;
  for (size_t i = 0; i < eng->loops.size(); i++) {
    const LoopTelemetry& t = eng->loops[i]->tel;
    for (int r = 0; r < FB_REASONS; r++) fb[r] += t.fallbacks[r];
    for (int r = 0; r < SFB_REASONS; r++) sfb[r] += t.sfallbacks[r];
    for (int s = 0; s < kDpStages; s++) {
      dp[s] += t.dp_copies[s];
      dpb[s] += t.dp_copy_bytes[s];
    }
    for (int ln = 0; ln < kLanes; ln++) {
      hist_merge(queue[ln], t.queue[ln]);
      hist_merge(shim[ln], t.shim[ln]);
      hist_merge(resid[ln], t.resid[ln]);
    }
    hist_merge(burst, t.burst);
    hist_merge(sburst, t.stream_burst);
    s_chunks_in += t.stream_chunks_in;
    s_feedbacks += t.stream_feedbacks;
    hist_merge(wiov, t.wiov);
    if (t.wq_hwm > wq_hwm) wq_hwm = t.wq_hwm;
    if (t.inbuf_hwm > inbuf_hwm) inbuf_hwm = t.inbuf_hwm;
    PyObject* lo = Py_BuildValue(
        "{s:K,s:K,s:K,s:K,s:K,s:K,s:K}",
        "busy_ns", (unsigned long long)t.busy_ns,
        "idle_ns", (unsigned long long)t.idle_ns,
        "polls", (unsigned long long)t.polls,
        "spin_polls", (unsigned long long)t.spin_polls,
        "accepts", (unsigned long long)t.accepts,
        "frames", (unsigned long long)t.frames,
        "handoffs", (unsigned long long)t.handoffs);
    if (!lo) {
      Py_DECREF(loops);
      return nullptr;
    }
    PyList_SET_ITEM(loops, (Py_ssize_t)i, lo);
  }
  // per-lane handled/errors roll up from the registered handlers
  uint64_t handled[kLanes] = {}, errors[kLanes] = {};
  PyObject* methods = PyDict_New();
  if (!methods) {
    Py_DECREF(loops);
    return nullptr;
  }
  for (auto& kv : eng->native_methods) {
    NativeMethod* m = kv.second;
    uint64_t cnt = m->count.load(std::memory_order_relaxed);
    uint64_t err = m->errors.load(std::memory_order_relaxed);
    uint64_t sop = m->stream_opens.load(std::memory_order_relaxed);
    uint64_t serr = m->stream_errors.load(std::memory_order_relaxed);
    if (m->kind == 2) {
      handled[LANE_RAW] += cnt;
      errors[LANE_RAW] += err;
    } else if (m->kind == 3) {
      handled[LANE_SLIM] += cnt;
      errors[LANE_SLIM] += err;
    }
    handled[LANE_STREAM] += sop;
    errors[LANE_STREAM] += serr;
    std::string name = kv.first;
    size_t z = name.find('\0');
    if (z != std::string::npos) name[z] = '.';
    PyObject* md = Py_BuildValue(
        "{s:i,s:K,s:K,s:K,s:K,s:K,s:K,s:K,s:K}", "kind", m->kind,
        "handled",
        (unsigned long long)cnt, "errors", (unsigned long long)err,
        "stream_opens", (unsigned long long)sop,
        "stream_errors", (unsigned long long)serr,
        "fb_rpc_att_over_cap",
        (unsigned long long)m->fb_att_over_cap.load(
            std::memory_order_relaxed),
        "fb_rpc_large_frame",
        (unsigned long long)m->fb_large_frame.load(
            std::memory_order_relaxed),
        "fb_rpc_trace_raw_lane",
        (unsigned long long)m->fb_trace_raw.load(
            std::memory_order_relaxed),
        "fb_stream_open",
        (unsigned long long)m->fb_stream_open.load(
            std::memory_order_relaxed));
    if (!md || PyDict_SetItemString(methods, name.c_str(), md) != 0) {
      Py_XDECREF(md);
      Py_DECREF(methods);
      Py_DECREF(loops);
      return nullptr;
    }
    Py_DECREF(md);
  }
  PyObject* routes = PyDict_New();
  if (!routes) {
    Py_DECREF(methods);
    Py_DECREF(loops);
    return nullptr;
  }
  for (auto& kv : eng->http_routes) {
    HttpRoute* r = kv.second;
    uint64_t cnt = r->count.load(std::memory_order_relaxed);
    uint64_t err = r->errors.load(std::memory_order_relaxed);
    handled[LANE_HTTP] += cnt;
    errors[LANE_HTTP] += err;
    std::string name = kv.first;
    size_t z = name.find('\0');
    if (z != std::string::npos) name[z] = ' ';
    PyObject* rd = Py_BuildValue(
        "{s:K,s:K}", "handled", (unsigned long long)cnt, "errors",
        (unsigned long long)err);
    bool ok = rd != nullptr;
    for (int i = 0; ok && i < kRouteFb; i++) {
      char key[48];
      snprintf(key, sizeof key, "fb_%s", kRouteFbNames[i]);
      ok = set_u64(rd, key,
                   r->fb[i].load(std::memory_order_relaxed)) == 0;
    }
    if (!ok || PyDict_SetItemString(routes, name.c_str(), rd) != 0) {
      Py_XDECREF(rd);
      Py_DECREF(routes);
      Py_DECREF(methods);
      Py_DECREF(loops);
      return nullptr;
    }
    Py_DECREF(rd);
  }
  PyObject* out = PyDict_New();
  PyObject* fbd = PyDict_New();
  PyObject* lanes = PyDict_New();
  bool ok = out && fbd && lanes;
  for (int r = 0; ok && r < FB_REASONS; r++)
    ok = set_u64(fbd, kFbNames[r], fb[r]) == 0;
  // kind-5 stream reasons ride the same fallback family (closed enum,
  // one flat dict for /native + the fallback_total bvar) AND the
  // dedicated streams section below
  for (int r = 0; ok && r < SFB_REASONS; r++)
    ok = set_u64(fbd, kStreamFbNames[r], sfb[r]) == 0;
  for (int ln = 0; ok && ln < kLanes; ln++) {
    PyObject* ld = PyDict_New();
    ok = ld != nullptr;
    if (ok) ok = set_u64(ld, "handled", handled[ln]) == 0;
    if (ok) ok = set_u64(ld, "errors", errors[ln]) == 0;
    if (ok) ok = set_hist(ld, "queue_us", queue[ln]) == 0;
    if (ok) ok = set_hist(ld, "shim_us", shim[ln]) == 0;
    if (ok) ok = set_hist(ld, "resid_us", resid[ln]) == 0;
    if (ok) ok = PyDict_SetItemString(lanes, kLaneNames[ln], ld) == 0;
    Py_XDECREF(ld);
  }
  if (ok) ok = PyDict_SetItemString(out, "fallbacks", fbd) == 0;
  if (ok) ok = PyDict_SetItemString(out, "lanes", lanes) == 0;
  if (ok) {
    // data-plane copy ledger: every engine-side payload memcpy ≥4KB by
    // stage — the zero-copy invariant tests diff this around a call
    PyObject* dpc = PyDict_New();
    PyObject* dpB = PyDict_New();
    ok = dpc && dpB;
    for (int s = 0; ok && s < kDpStages; s++) {
      ok = set_u64(dpc, kDpNames[s], dp[s]) == 0
           && set_u64(dpB, kDpNames[s], dpb[s]) == 0;
    }
    if (ok) ok = PyDict_SetItemString(out, "data_plane_copies", dpc) == 0;
    if (ok)
      ok = PyDict_SetItemString(out, "data_plane_copy_bytes", dpB) == 0;
    Py_XDECREF(dpc);
    Py_XDECREF(dpB);
  }
  if (ok) {
    // loop-pinning map: conn id -> {loop index, frames parsed}.  The
    // id/loop/frames triples snapshot under cmu into plain C++ storage
    // FIRST (no Python allocation while the lock is held: an
    // allocation-triggered GC finalizer calling back into the engine
    // would self-deadlock on the non-recursive mutex), then
    // materialize.  Loop ownership is fixed at accept; frame counts
    // are racy monotonic reads, same discipline as the rest.
    struct ConnSnap { uint64_t id; int loop; uint64_t frames; };
    std::vector<ConnSnap> snap;
    {
      std::lock_guard<std::mutex> g(eng->cmu);
      snap.reserve(eng->by_id.size());
      for (auto& kv : eng->by_id) {
        Conn* c = kv.second;
        snap.push_back({kv.first, c->loop ? c->loop->index : -1,
                        c->frames});
      }
    }
    PyObject* conns = PyDict_New();
    ok = conns != nullptr;
    for (size_t i = 0; ok && i < snap.size(); i++) {
      PyObject* key = PyLong_FromUnsignedLongLong(snap[i].id);
      PyObject* cd = Py_BuildValue(
          "{s:i,s:K}", "loop", snap[i].loop, "frames",
          (unsigned long long)snap[i].frames);
      ok = key != nullptr && cd != nullptr
           && PyDict_SetItem(conns, key, cd) == 0;
      Py_XDECREF(key);
      Py_XDECREF(cd);
    }
    if (ok) ok = PyDict_SetItemString(out, "conns", conns) == 0;
    Py_XDECREF(conns);
  }
  if (ok) {
    // kind-5 streaming section: streams open, chunk/burst/credit
    // accounting — the /native "streaming" block and the
    // native_stream_* bvars read this
    PyObject* sd = PyDict_New();
    ok = sd != nullptr;
    if (ok)
      ok = set_u64(sd, "open",
                   (uint64_t)eng->nstreams.load(
                       std::memory_order_relaxed)) == 0;
    if (ok) ok = set_u64(sd, "chunks_in", s_chunks_in) == 0;
    if (ok) ok = set_u64(sd, "feedbacks_in", s_feedbacks) == 0;
    if (ok)
      ok = set_u64(sd, "chunks_out",
                   eng->s_chunks_out.load(
                       std::memory_order_relaxed)) == 0;
    if (ok)
      ok = set_u64(sd, "chunk_bytes_out",
                   eng->s_chunk_bytes_out.load(
                       std::memory_order_relaxed)) == 0;
    if (ok)
      ok = set_u64(sd, "credit_stalls",
                   eng->s_credit_stalls.load(
                       std::memory_order_relaxed)) == 0;
    if (ok)
      ok = set_u64(sd, "write_batches",
                   eng->s_write_batches.load(
                       std::memory_order_relaxed)) == 0;
    if (ok) ok = set_hist(sd, "chunk_burst", sburst) == 0;
    if (ok) {
      PyObject* sfd = PyDict_New();
      ok = sfd != nullptr;
      for (int r = 0; ok && r < SFB_REASONS; r++)
        ok = set_u64(sfd, kStreamFbNames[r], sfb[r]) == 0;
      if (ok) ok = PyDict_SetItemString(sd, "fallbacks", sfd) == 0;
      Py_XDECREF(sfd);
    }
    if (ok) ok = PyDict_SetItemString(out, "streams", sd) == 0;
    Py_XDECREF(sd);
  }
  if (ok) ok = set_hist(out, "burst", burst) == 0;
  if (ok) ok = set_hist(out, "writev_iov", wiov) == 0;
  if (ok) ok = set_u64(out, "wq_hwm", wq_hwm) == 0;
  if (ok) ok = set_u64(out, "inbuf_hwm", inbuf_hwm) == 0;
  if (ok) ok = PyDict_SetItemString(out, "loops", loops) == 0;
  if (ok) ok = PyDict_SetItemString(out, "methods", methods) == 0;
  if (ok) ok = PyDict_SetItemString(out, "routes", routes) == 0;
  Py_XDECREF(fbd);
  Py_XDECREF(lanes);
  Py_DECREF(loops);
  Py_DECREF(methods);
  Py_DECREF(routes);
  if (!ok) {
    Py_XDECREF(out);
    return nullptr;
  }
  return out;
}

static PyObject* Engine_send(EngineObj* self, PyObject* args) {
  unsigned long long id;
  PyObject* parts;
  if (!PyArg_ParseTuple(args, "KO", &id, &parts)) return nullptr;
  EngineImpl* eng = self->eng;
  Conn* c = nullptr;
  {
    std::lock_guard<std::mutex> g(eng->cmu);
    auto it = eng->by_id.find(id);
    if (it != eng->by_id.end()) c = it->second;
  }
  if (!c || c->dead || c->closing) {
    PyErr_SetString(PyExc_ConnectionError, "connection gone");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(parts, "parts must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  bool try_inline = false;
  {
    std::lock_guard<std::mutex> g(c->wmu);
    bool was_empty = c->wq.empty();
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
      WriteItem it;
      if (PyObject_GetBuffer(item, &it.view, PyBUF_SIMPLE) != 0) {
        Py_DECREF(seq);
        return nullptr;
      }
      if (it.view.len == 0) {
        PyBuffer_Release(&it.view);
        continue;
      }
      c->wq.push_back(it);
    }
    // "write once before KeepWrite" (≈ socket.cpp:1649): when this
    // thread is the sole writer and the payload is small, one inline
    // writev usually drains the whole queue and saves the wake +
    // loop-thread handoff.  The GIL stays HELD: it is what serializes
    // this path against conn_destroy's delete (and the 64KB cap bounds
    // the hold time); nonblocking writev never sleeps.
    //
    // EXCEPTION: on the conn's own loop thread (usercode_inline
    // dispatch mid-parse-burst) the flush is DEFERRED to the loop
    // iteration instead, coalescing a whole pipelined burst of
    // responses into few writevs — otherwise every response wakes the
    // blocked peer and costs two context switches per message.
    size_t queued = 0;
    for (auto& it2 : c->wq) queued += it2.view.len - it2.offset;
    try_inline = was_empty && !c->wq.empty() && queued <= 65536
                 && t_current_loop != c->loop && !c->dead && c->fd >= 0;
    if (try_inline) {
      struct iovec iov[64];
      int ni = 0;
      for (auto it2 = c->wq.begin(); it2 != c->wq.end() && ni < 64;
           ++it2, ++ni) {
        iov[ni].iov_base = (char*)it2->view.buf + it2->offset;
        iov[ni].iov_len = it2->view.len - it2->offset;
      }
      ssize_t w = writev(c->fd, iov, ni);
      if (w > 0) {
        eng->bytes_out += (uint64_t)w;
        size_t left = (size_t)w;
        while (left > 0 && !c->wq.empty()) {
          WriteItem& it3 = c->wq.front();
          size_t avail = it3.view.len - it3.offset;
          if (left >= avail) {
            left -= avail;
            complete_item(c->loop, it3, /*gil_held=*/true);
            c->wq.pop_front();
          } else {
            it3.offset += left;
            left = 0;
          }
        }
      }
      // fatal errors are left to the owning loop's flush to detect
    }
    if (c->wq.empty()) {
      Py_DECREF(seq);
      Py_RETURN_NONE;
    }
  }
  Py_DECREF(seq);
  // hand the remaining flush to the owning loop — the lock-free
  // cross-loop completion handoff (coalesced: the flush_queued CAS
  // admits one node per conn per loop iteration)
  Loop* lp = c->loop;
  bool expect = false;
  if (c->flush_queued.compare_exchange_strong(
          expect, true, std::memory_order_acq_rel))
    loop_post(lp, c->id, HO_FLUSH);
  Py_RETURN_NONE;
}

static PyObject* Engine_close_conn(EngineObj* self, PyObject* args) {
  unsigned long long id;
  if (!PyArg_ParseTuple(args, "K", &id)) return nullptr;
  EngineImpl* eng = self->eng;
  Conn* c = nullptr;
  {
    std::lock_guard<std::mutex> g(eng->cmu);
    auto it = eng->by_id.find(id);
    if (it != eng->by_id.end()) c = it->second;
  }
  if (c) loop_post(c->loop, id, HO_CLOSE);
  Py_RETURN_NONE;
}

static PyObject* Engine_stop(EngineObj* self, PyObject*) {
  EngineImpl* eng = self->eng;
  eng->stopping = true;
  for (Loop* lp : eng->loops) loop_wake(lp);
  Py_BEGIN_ALLOW_THREADS;
  for (Loop* lp : eng->loops) {
    if (lp->thr.joinable()) lp->thr.join();
  }
  Py_END_ALLOW_THREADS;
  Py_RETURN_NONE;
}

static PyObject* Engine_stats(EngineObj* self, PyObject*) {
  EngineImpl* eng = self->eng;
  size_t nconns;
  {
    std::lock_guard<std::mutex> g(eng->cmu);
    nconns = eng->by_id.size();
  }
  return Py_BuildValue(
      "{s:K,s:K,s:K,s:n}", "messages", (unsigned long long)eng->nmessages,
      "bytes_in", (unsigned long long)eng->bytes_in, "bytes_out",
      (unsigned long long)eng->bytes_out, "connections", (Py_ssize_t)nconns);
}

static void Engine_dealloc(EngineObj* self) {
  if (self->eng) {
    self->eng->stopping = true;
    for (Loop* lp : self->eng->loops) loop_wake(lp);
    Py_BEGIN_ALLOW_THREADS;
    for (Loop* lp : self->eng->loops)
      if (lp->thr.joinable()) lp->thr.join();
    Py_END_ALLOW_THREADS;
    for (Loop* lp : self->eng->loops) {
      // nodes posted after the loop thread drained its last batch
      // (close_conn during teardown): free, nothing left to run them
      HandoffNode* head =
          lp->handoff_head.exchange(nullptr, std::memory_order_acquire);
      while (head) {
        HandoffNode* nx = head->next;
        delete head;
        head = nx;
      }
      close(lp->epfd);
      close(lp->wakefd);
      delete lp;
    }
    for (auto& kv : self->eng->native_methods) {
      Py_XDECREF(kv.second->handler);
      Py_XDECREF(kv.second->stream_handler);
      delete kv.second;
    }
    for (auto& kv : self->eng->http_routes) {
      Py_XDECREF(kv.second->handler);
      delete kv.second;
    }
    Py_XDECREF(self->eng->dispatch);
    Py_XDECREF(self->eng->burst_end);
    Py_XDECREF(self->eng->stream_chunks);
    delete self->eng;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static PyMethodDef Engine_methods[] = {
    {"listen", (PyCFunction)Engine_listen, METH_VARARGS,
     "adopt a bound+listening fd"},
    {"listen_sharded", (PyCFunction)Engine_listen_sharded, METH_VARARGS,
     "listen_sharded(fds) — one SO_REUSEPORT-bound listening fd per "
     "loop; each loop accepts and pins its own connections"},
    {"set_lame_duck", (PyCFunction)Engine_set_lame_duck, METH_VARARGS,
     "set_lame_duck(mode) — drain: 0 off, 1 = accept pause only, 2 = "
     "pause + lame-duck TLV on native responses + kind-4 declines; "
     "listener fds stay open for a hot-restart successor"},
    {"listener_fds", (PyCFunction)Engine_listener_fds, METH_NOARGS,
     "listener_fds() -> [fd] — bound listening fds for hot-restart "
     "fd passing"},
    {"set_busy_poll_us", (PyCFunction)Engine_set_busy_poll_us,
     METH_VARARGS,
     "set_busy_poll_us(us) — spin this long on zero-timeout polls "
     "before each blocking epoll_wait (0 disables; runtime-settable)"},
    {"run_loop", (PyCFunction)Engine_run_loop, METH_VARARGS,
     "run one event loop on the calling (Python) thread until stop()"},
    {"set_http_max_body", (PyCFunction)Engine_set_http_max_body,
     METH_VARARGS, "cap HTTP request bodies (mirrors max_body_size)"},
    {"set_domain_tlv", (PyCFunction)Engine_set_domain_tlv, METH_VARARGS,
     "pre-encoded local ici-domain TLV for kind-3 domain-exchange "
     "answers; pre-listen only"},
    {"send", (PyCFunction)Engine_send, METH_VARARGS,
     "queue buffers for vectored write on a connection"},
    {"close_conn", (PyCFunction)Engine_close_conn, METH_VARARGS, nullptr},
    {"stop", (PyCFunction)Engine_stop, METH_NOARGS, nullptr},
    {"stats", (PyCFunction)Engine_stats, METH_NOARGS, nullptr},
    {"register_native_method", (PyCFunction)Engine_register_native_method,
     METH_VARARGS,
     "register_native_method(svc, mth, kind, data=b'') — answer the "
     "method in C++ (kind 0=echo, 1=const); pre-listen only"},
    {"set_native_dispatch", (PyCFunction)Engine_set_native_dispatch,
     METH_VARARGS, "enable/disable GIL-free native dispatch at runtime"},
    {"set_burst_end", (PyCFunction)Engine_set_burst_end, METH_VARARGS,
     "set_burst_end(callable|None) — per-burst accounting epilogue "
     "called once after each batched shim entry; pre-listen only"},
    {"set_stream_shim", (PyCFunction)Engine_set_stream_shim,
     METH_VARARGS,
     "set_stream_shim(svc, mth, handler) — kind-5 stream-OPEN shim "
     "for a registered kind-3 method; pre-listen only"},
    {"set_stream_chunks", (PyCFunction)Engine_set_stream_chunks,
     METH_VARARGS,
     "set_stream_chunks(callable|None) — batched chunk delivery: one "
     "call per read burst with [(sid, flags, payload)]; pre-listen "
     "only"},
    {"set_stream_mode", (PyCFunction)Engine_set_stream_mode,
     METH_VARARGS,
     "set_stream_mode(mode) — 0 lane off, 1 on, 2 declined "
     "(non-inline server); names the kind-5 fallback reason"},
    {"stream_register", (PyCFunction)Engine_stream_register,
     METH_VARARGS,
     "stream_register(conn_id, sid, peer_sid, window) — adopt an "
     "accepted stream onto the kind-5 lane (write credit accounted "
     "in C++)"},
    {"stream_unregister", (PyCFunction)Engine_stream_unregister,
     METH_VARARGS,
     "stream_unregister(sid) -> bool — drop a stream from the lane; "
     "blocked producers wake closed"},
    {"stream_write", (PyCFunction)Engine_stream_write, METH_VARARGS,
     "stream_write(sid, payload, timeout_ms=10000) -> 0 ok | -1 "
     "credit exhaustion | -2 closed/unknown"},
    {"stream_write_many", (PyCFunction)Engine_stream_write_many,
     METH_VARARGS,
     "stream_write_many([(sid, payload)], timeout_ms=10000) -> "
     "[status] — chunk-coalesced burst write: one owned buffer and "
     "one writev per connection"},
    {"register_http_route", (PyCFunction)Engine_register_http_route,
     METH_VARARGS,
     "register_http_route(method, path, handler) — slim HTTP lane "
     "route (kind 4); pre-listen only"},
    {"set_http_slim", (PyCFunction)Engine_set_http_slim, METH_VARARGS,
     "enable/disable the slim HTTP lane at runtime"},
    {"http_slim_stats", (PyCFunction)Engine_http_slim_stats,
     METH_VARARGS,
     "http_slim_stats([method, path]) — per-route (handled, errors) "
     "counters for the slim HTTP lane; no args returns the whole map"},
    {"native_stats", (PyCFunction)Engine_native_stats, METH_VARARGS,
     "native_stats([svc, mth]) — per-method (answered, errors) counters "
     "for native dispatch; no args returns the whole map"},
    {"telemetry", (PyCFunction)Engine_telemetry, METH_NOARGS,
     "telemetry() — the whole always-on observability table in one "
     "snapshot: per-lane stage histograms, reason-coded fallback "
     "counters, burst/writev distributions, high-water marks, loop "
     "busy/idle time, per-method and per-route breakdowns"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject EngineType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------------------------------------------------------------------------
// sync_call: the client-side latency fast path.  writev the request parts,
// then block (poll) until exactly one complete TRPC frame is read, all with
// the GIL released.  The caller owns the connection exclusively (pooled /
// short connections) so no other reader races with us.  Returns
// (NativeBuf(meta+payload), meta_size).
// ---------------------------------------------------------------------------

#include <poll.h>

// one recv into buf[*got..cap], blocking on the deadline when the socket
// is dry.  Returns 0 ok (>=1 byte appended), 1 timeout, 2 conn error.
static int wait_fd(int fd, short events, int64_t deadline_ms);
static int recv_more(int fd, char* buf, size_t* got, size_t cap,
                     int64_t deadline, char* errbuf, size_t errcap) {
  for (;;) {
    ssize_t r = recv(fd, buf + *got, cap - *got, 0);
    if (r > 0) { *got += (size_t)r; return 0; }
    if (r == 0) { snprintf(errbuf, errcap, "connection closed by peer"); return 2; }
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      int pr = wait_fd(fd, POLLIN, deadline);
      if (pr == 0) return 1;
      if (pr < 0) { snprintf(errbuf, errcap, "poll: %s", strerror(errno)); return 2; }
      continue;
    }
    if (errno == EINTR) continue;
    snprintf(errbuf, errcap, "read: %s", strerror(errno));
    return 2;
  }
}

// poll helper honoring an absolute deadline (ms, CLOCK_MONOTONIC); -1 = none
static int wait_fd(int fd, short events, int64_t deadline_ms) {
  struct pollfd p;
  p.fd = fd;
  p.events = events;
  for (;;) {
    int tmo = -1;
    if (deadline_ms >= 0) {
      int64_t left = deadline_ms - now_ms();
      if (left <= 0) return 0;  // timed out
      tmo = (int)(left > 1000000 ? 1000000 : left);
    }
    int r = poll(&p, 1, tmo);
    if (r > 0) return 1;
    if (r == 0) {
      if (deadline_ms < 0) continue;
      return 0;
    }
    if (errno == EINTR) continue;
    return -1;
  }
}

// ---- client request frame layout (single source, shared by raw_call
// and scatter_call) ----

// remaining-deadline TLV; returns its length (0 when no timeout)
static size_t build_tmo_tlv(char* tmo, int timeout_ms) {
  if (timeout_ms <= 0) return 0;
  uint32_t l4 = 4;
  tmo[0] = 13;
  memcpy(tmo + 1, &l4, 4);
  uint32_t t32 = (uint32_t)timeout_ms;
  memcpy(tmo + 5, &t32, 4);
  return 9;
}

// TRPC header + cid TLV + [att TLV] into head (>= 34 bytes); the
// cached tail TLVs, the tmo TLV and the payload/attachment ride their
// own iovs — mlen covers cid/att TLVs + tail_len + tmo_len.  Returns
// the head length.
static size_t build_request_head(char* head, uint64_t cid, size_t alen,
                                 size_t tail_len, size_t tmo_len,
                                 size_t payload_len) {
  char* w = head + kHeaderSize;
  uint32_t l8 = 8, l4 = 4;
  *w = 1;
  memcpy(w + 1, &l8, 4);
  memcpy(w + 5, &cid, 8);
  w += 13;
  if (alen) {
    *w = 3;
    memcpy(w + 1, &l4, 4);
    uint32_t a32 = (uint32_t)alen;
    memcpy(w + 5, &a32, 4);
    w += 9;
  }
  uint32_t mlen = (uint32_t)((size_t)(w - head - kHeaderSize) + tail_len
                             + tmo_len);
  uint32_t body = mlen + (uint32_t)payload_len + (uint32_t)alen;
  memcpy(head, "TRPC", 4);
  memcpy(head + 4, &body, 4);
  memcpy(head + 8, &mlen, 4);
  return (size_t)(w - head);
}

// Scan a response meta for the PLAIN success shape — cid(1)/att(3)/
// ici-domain(15) tags only.  True = plain; rcid/ratt/dom filled.
// Anything else goes back to Python whole for the full RpcMeta decode.
static bool scan_plain_resp(const char* p, size_t meta, uint64_t* rcid,
                            uint32_t* ratt, const char** dom,
                            uint32_t* dom_len) {
  bool plain = true;
  size_t off = 0;
  while (off < meta) {
    if (off + 5 > meta) return false;
    uint8_t tag = (uint8_t)p[off];
    uint32_t ln;
    memcpy(&ln, p + off + 1, 4);
    off += 5;
    if (ln > meta || off + ln > meta) return false;
    if (tag == 1 && ln == 8) memcpy(rcid, p + off, 8);
    else if (tag == 3 && ln == 4) memcpy(ratt, p + off, 4);
    else if (tag == 15) { *dom = p + off; *dom_len = ln; }
    else plain = false;
    off += ln;
  }
  return plain;
}

// Write an iovec array fully (poll on EAGAIN, resume partials) with
// the GIL released by the CALLER.  Shared by sync_call and raw_call.
// Returns the shared error code convention.
static int write_all_iov(int fd, struct iovec* iov, int n,
                         int64_t deadline, char* errbuf, size_t errcap) {
  int err = 0;
  int first = 0;
  while (first < n && !err) {
    ssize_t w = writev(fd, iov + first, n - first);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int r = wait_fd(fd, POLLOUT, deadline);
        if (r == 0) err = 1;
        else if (r < 0) {
          err = 2;
          snprintf(errbuf, errcap, "poll: %s", strerror(errno));
        }
        continue;
      }
      if (errno == EINTR) continue;
      err = 2;
      snprintf(errbuf, errcap, "write: %s", strerror(errno));
      break;
    }
    size_t left = (size_t)w;
    while (left > 0 && first < n) {
      if (left >= iov[first].iov_len) {
        left -= iov[first].iov_len;
        first++;
      } else {
        iov[first].iov_base = (char*)iov[first].iov_base + left;
        iov[first].iov_len -= left;
        left = 0;
      }
    }
  }
  return err;
}


// Read exactly one TRPC response frame off an exclusively-owned fd,
// consuming TICI credit-return frames anywhere around it (leading:
// in-handler redeems piggyback in front of the response; trailing:
// lazy redeems ride behind — both must drain to a frame boundary or
// the connection desyncs).  Called WITH the GIL held; IO runs with it
// released.  On success *out_buf is a fresh NativeBuf holding the
// frame body and *out_meta its meta size.  Returns the shared error
// code convention (0 ok, 1 timeout, 2 conn error, 3 bad frame).
//
// NOTE: the TICI parse appears twice below (leading drain interleaved
// with the header hunt, trailing drain after the body) — the two
// loops share the frame format and the cnt>8000 bound; a change to
// either MUST be mirrored in the other (and in call_batch's drains).
static int read_one_response(int fd, int64_t deadline, NativeBuf** out_buf,
                             uint32_t* out_meta,
                             std::vector<uint64_t>& ack_vec,
                             char* errbuf, size_t errcap) {
  int err = 0;
  char scratch[65536];       // greedy-read landing zone (header + body)
  size_t got = 0;
  uint32_t body = 0, meta = 0;
  *out_buf = nullptr;

  Py_BEGIN_ALLOW_THREADS;
  while (!err) {
    while (!err && got < 8)
      err = recv_more(fd, scratch, &got, sizeof scratch, deadline,
                      errbuf, errcap);
    if (err) break;
    if (memcmp(scratch, "TICI", 4) == 0) {
      uint32_t cnt = 0;
      memcpy(&cnt, scratch + 4, 4);
      size_t total = 8 + 8ul * cnt;
      if (cnt > 8000 || total > sizeof scratch) {
        err = 3;
        snprintf(errbuf, errcap, "oversized ack frame cnt=%u", cnt);
        break;
      }
      while (!err && got < total)
        err = recv_more(fd, scratch, &got, sizeof scratch, deadline,
                        errbuf, errcap);
      if (err) break;
      for (uint32_t i = 0; i < cnt; i++) {
        uint64_t id;
        memcpy(&id, scratch + 8 + 8ul * i, 8);
        ack_vec.push_back(id);
      }
      memmove(scratch, scratch + total, got - total);
      got -= total;
      continue;
    }
    while (!err && got < kHeaderSize)
      err = recv_more(fd, scratch, &got, sizeof scratch, deadline,
                      errbuf, errcap);
    if (err) break;
    if (memcmp(scratch, "TRPC", 4) != 0) {
      err = 3;
      snprintf(errbuf, errcap, "unexpected magic on fast-path read");
    } else {
      memcpy(&body, scratch + 4, 4);
      memcpy(&meta, scratch + 8, 4);
      if (body > kMaxBody || meta > body) {
        err = 3;
        snprintf(errbuf, errcap, "bad frame sizes body=%u meta=%u",
                 body, meta);
      }
    }
    break;
  }
  Py_END_ALLOW_THREADS;
  if (err) return err;

  NativeBuf* out = nativebuf_new((Py_ssize_t)body);   // GIL held again
  if (!out) {
    snprintf(errbuf, errcap, "out of memory");
    return 2;
  }
  size_t have = got - kHeaderSize;           // surplus from the greedy read
  if (have > (size_t)body) have = body;
  if (have) memcpy(out->data, scratch + kHeaderSize, have);
  Py_BEGIN_ALLOW_THREADS;
  size_t filled = have;
  while (filled < body && !err) {
    ssize_t r = recv(fd, out->data + filled, body - filled, 0);
    if (r == 0) {
      err = 2;
      snprintf(errbuf, errcap, "connection closed mid-frame");
      break;
    }
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int pr = wait_fd(fd, POLLIN, deadline);
        if (pr == 0) err = 1;
        else if (pr < 0) {
          err = 2;
          snprintf(errbuf, errcap, "poll: %s", strerror(errno));
        }
        continue;
      }
      if (errno == EINTR) continue;
      err = 2;
      snprintf(errbuf, errcap, "read: %s", strerror(errno));
      break;
    }
    filled += (size_t)r;
  }
  // trailing TICI frames the greedy read pulled in past the response:
  // the response is already complete, so a nearly-expired deadline must
  // not fail the call over bytes already in flight — small grace window
  size_t tail_off = kHeaderSize + (size_t)body;
  if (!err && got > tail_off) {
    int64_t tdl = deadline;
    if (tdl >= 0) {
      int64_t grace = now_ms() + 2000;
      if (tdl < grace) tdl = grace;
    }
    size_t tgot = got - tail_off;
    memmove(scratch, scratch + tail_off, tgot);
    while (!err && tgot > 0) {
      while (!err && tgot < 8)
        err = recv_more(fd, scratch, &tgot, sizeof scratch, tdl,
                        errbuf, errcap);
      if (err) break;
      if (memcmp(scratch, "TICI", 4) != 0) {
        err = 3;
        snprintf(errbuf, errcap, "unexpected trailing bytes after response");
        break;
      }
      uint32_t cnt = 0;
      memcpy(&cnt, scratch + 4, 4);
      size_t total = 8 + 8ul * cnt;
      if (cnt > 8000 || total > sizeof scratch) {
        err = 3;
        snprintf(errbuf, errcap, "oversized ack frame cnt=%u", cnt);
        break;
      }
      while (!err && tgot < total)
        err = recv_more(fd, scratch, &tgot, sizeof scratch, tdl,
                        errbuf, errcap);
      if (err) break;
      for (uint32_t i = 0; i < cnt; i++) {
        uint64_t id;
        memcpy(&id, scratch + 8 + 8ul * i, 8);
        ack_vec.push_back(id);
      }
      memmove(scratch, scratch + total, tgot - total);
      tgot -= total;
    }
  }
  Py_END_ALLOW_THREADS;
  if (err) {
    Py_DECREF((PyObject*)out);
    return err;
  }
  *out_buf = out;
  *out_meta = meta;
  return 0;
}


static PyObject* sync_call(PyObject*, PyObject* args) {
  int fd;
  PyObject* parts;
  double timeout_s = -1.0;
  if (!PyArg_ParseTuple(args, "iO|d", &fd, &parts, &timeout_s))
    return nullptr;
  PyObject* seq = PySequence_Fast(parts, "parts must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t nparts = PySequence_Fast_GET_SIZE(seq);
  if (nparts > 62) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "too many request parts");
    return nullptr;
  }
  Py_buffer views[62];
  Py_ssize_t nviews = 0;
  for (Py_ssize_t i = 0; i < nparts; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(item, &views[nviews], PyBUF_SIMPLE) != 0) {
      for (Py_ssize_t j = 0; j < nviews; j++) PyBuffer_Release(&views[j]);
      Py_DECREF(seq);
      return nullptr;
    }
    if (views[nviews].len > 0) nviews++;
    else PyBuffer_Release(&views[nviews]);
  }
  int64_t deadline = timeout_s >= 0 ? now_ms() + (int64_t)(timeout_s * 1000)
                                    : -1;
  // phase 1: write all parts (vectored, poll on EAGAIN)
  int err = 0;               // 0 ok, 1 timeout, 2 conn error, 3 bad frame
  char errbuf[96] = {0};
  uint32_t meta = 0;
  NativeBuf* out = nullptr;
  std::vector<uint64_t> ack_vec;  // TICI credit-returns around the response

  Py_BEGIN_ALLOW_THREADS;
  struct iovec iov[62];
  int n = 0;
  for (Py_ssize_t i = 0; i < nviews; i++) {
    iov[n].iov_base = views[i].buf;
    iov[n].iov_len = views[i].len;
    n++;
  }
  err = write_all_iov(fd, iov, n, deadline, errbuf, sizeof errbuf);
  Py_END_ALLOW_THREADS;
  // phase 2+3: one response frame + surrounding TICI drains (shared
  // with raw_call — read_one_response owns the discipline; GIL held at
  // entry, released around its IO)
  if (!err)
    err = read_one_response(fd, deadline, &out, &meta, ack_vec,
                            errbuf, sizeof errbuf);

  for (Py_ssize_t j = 0; j < nviews; j++) PyBuffer_Release(&views[j]);
  Py_DECREF(seq);
  if (err) {
    Py_XDECREF((PyObject*)out);
    if (err == 1)
      PyErr_SetString(PyExc_TimeoutError, "rpc deadline exceeded");
    else if (err == 2)
      PyErr_SetString(PyExc_ConnectionError, errbuf);
    else
      PyErr_SetString(PyExc_ValueError, errbuf);
    return nullptr;
  }
  if (!ack_vec.empty()) {
    PyObject* acks = PyList_New((Py_ssize_t)ack_vec.size());
    if (!acks) { Py_DECREF((PyObject*)out); return nullptr; }
    for (size_t i = 0; i < ack_vec.size(); i++)
      PyList_SET_ITEM(acks, (Py_ssize_t)i,
                      PyLong_FromUnsignedLongLong(ack_vec[i]));
    return Py_BuildValue("(NkN)", (PyObject*)out, (unsigned long)meta, acks);
  }
  PyObject* tup = Py_BuildValue("(Nk)", (PyObject*)out, (unsigned long)meta);
  return tup;
}

// raw_call(fd, tail, payload, attachment, timeout_ms, cid, lead)
//   -> (ok, a, b, dom, acks)
//
// The client half of the raw latency lane, fully native: builds the
// request frame (cid TLV + optional attachment TLV + the channel's
// cached tail + optional remaining-deadline TLV), writes it vectored,
// reads the response, and scans its meta — Python's per-call work
// drops to generating a cid and unpacking one tuple.
//
//   ok=True : a = NativeBuf(payload+attachment), b = attachment size,
//             dom = peer ici-domain bytes or None
//   ok=False: a = NativeBuf(whole frame body), b = meta size (full
//             RpcMeta decode in Python — errors etc.), dom = None
//   acks    : TICI credit-return ids consumed around the response, or
//             None
static PyObject* raw_call(PyObject*, PyObject* args) {
  int fd;
  Py_buffer tail = {}, payload = {}, att = {}, lead = {};
  int timeout_ms;
  unsigned long long cid;
  PyObject* att_obj;
  PyObject* lead_obj = Py_None;
  if (!PyArg_ParseTuple(args, "iy*y*OiK|O", &fd, &tail, &payload,
                        &att_obj, &timeout_ms, &cid, &lead_obj)) {
    if (tail.obj) PyBuffer_Release(&tail);
    if (payload.obj) PyBuffer_Release(&payload);
    return nullptr;
  }
  auto release_all = [&]() {
    PyBuffer_Release(&tail);
    PyBuffer_Release(&payload);
    if (att.obj) PyBuffer_Release(&att);
    if (lead.obj) PyBuffer_Release(&lead);
  };
  if (att_obj != Py_None
      && PyObject_GetBuffer(att_obj, &att, PyBUF_SIMPLE) != 0) {
    PyBuffer_Release(&tail);
    PyBuffer_Release(&payload);
    return nullptr;
  }
  if (lead_obj != Py_None
      && PyObject_GetBuffer(lead_obj, &lead, PyBUF_SIMPLE) != 0) {
    release_all();
    return nullptr;
  }
  size_t alen = att.obj ? (size_t)att.len : 0;
  // Bound the WHOLE body (meta TLVs + tail + payload + attachment), not
  // the parts individually: a 400MB payload + 400MB attachment would
  // otherwise build a frame the server rejects, failing the pinned
  // connection instead of raising here (call_batch's fail-fast rule).
  if ((size_t)payload.len + alen + (size_t)tail.len + 31
      > (size_t)kMaxBody) {
    release_all();
    PyErr_SetString(PyExc_ValueError,
                    "payload + attachment exceeds max body");
    return nullptr;
  }

  // head block: TRPC header + cid TLV + [att TLV]; the cached tail and
  // the tmo TLV ride their own iovs (single-source frame layout —
  // build_request_head is shared with scatter_call)
  char head[40];
  char tmo[9];
  size_t tmo_len = build_tmo_tlv(tmo, timeout_ms);
  size_t head_len = build_request_head(head, cid, alen, (size_t)tail.len,
                                       tmo_len, (size_t)payload.len);

  int64_t deadline = timeout_ms > 0 ? now_ms() + timeout_ms : -1;
  int err = 0;
  char errbuf[96] = {0};
  uint32_t meta = 0;
  NativeBuf* out = nullptr;
  std::vector<uint64_t> ack_vec;

  Py_BEGIN_ALLOW_THREADS;
  struct iovec iov[6];
  int n = 0;
  if (lead.obj && lead.len > 0) iov[n++] = {lead.buf, (size_t)lead.len};
  iov[n++] = {head, head_len};
  if (tail.len > 0) iov[n++] = {tail.buf, (size_t)tail.len};
  if (tmo_len) iov[n++] = {tmo, tmo_len};
  if (payload.len > 0) iov[n++] = {payload.buf, (size_t)payload.len};
  if (alen) iov[n++] = {att.buf, (size_t)att.len};
  err = write_all_iov(fd, iov, n, deadline, errbuf, sizeof errbuf);
  Py_END_ALLOW_THREADS;

  if (!err)
    err = read_one_response(fd, deadline, &out, &meta, ack_vec,
                            errbuf, sizeof errbuf);
  release_all();
  if (err) {
    Py_XDECREF((PyObject*)out);
    if (err == 1)
      PyErr_SetString(PyExc_TimeoutError, "rpc deadline exceeded");
    else if (err == 2)
      PyErr_SetString(PyExc_ConnectionError, errbuf);
    else
      PyErr_SetString(PyExc_ValueError, errbuf);
    return nullptr;
  }

  // scan the response meta: plain success (cid/att/domain only, cid
  // matching) unpacks here; anything else goes back whole for RpcMeta
  uint64_t rcid = 0;
  uint32_t ratt = 0;
  const char* dom = nullptr;
  uint32_t dom_len = 0;
  bool plain = scan_plain_resp(out->data, meta, &rcid, &ratt, &dom,
                               &dom_len);
  PyObject* acks = Py_None;
  if (!ack_vec.empty()) {
    acks = PyList_New((Py_ssize_t)ack_vec.size());
    if (!acks) { Py_DECREF((PyObject*)out); return nullptr; }
    for (size_t i = 0; i < ack_vec.size(); i++)
      PyList_SET_ITEM(acks, (Py_ssize_t)i,
                      PyLong_FromUnsignedLongLong(ack_vec[i]));
  } else {
    Py_INCREF(Py_None);
  }
  size_t blen = (size_t)out->size - meta;
  if (plain && rcid == cid && ratt <= blen) {
    // the domain bytes live in the meta region — materialize them
    // BEFORE the body is shifted over it
    PyObject* dom_obj;
    if (dom_len) {
      dom_obj = PyBytes_FromStringAndSize(dom, (Py_ssize_t)dom_len);
      if (!dom_obj) {
        Py_DECREF((PyObject*)out);
        Py_DECREF(acks);
        return nullptr;
      }
    } else {
      dom_obj = Py_None;
      Py_INCREF(Py_None);
    }
    // shift the body down in place: the payload view Python receives
    // must start at offset 0 (NativeBuf has no offset concept)
    memmove(out->data, out->data + meta, blen);
    out->size = (Py_ssize_t)blen;
    return Py_BuildValue("(ONkNN)", Py_True, (PyObject*)out,
                         (unsigned long)ratt, dom_obj, acks);
  }
  return Py_BuildValue("(ONkON)", Py_False, (PyObject*)out,
                       (unsigned long)meta, Py_None, acks);
}


// scatter_call(items, timeout_s) -> [result, ...]
//
// The fan-out fast lane for ParallelChannel (≈ the reference's
// parallel_channel.h scatter): items is a sequence of
// (fd, tail, payload, att_or_None, cid, lead_or_None).  ALL request
// frames are built and written first (wire-level scatter — every
// branch's server starts working), then one response frame is read per
// fd in item order, so the whole fan-out costs Python ONE call instead
// of one build+write+read round per branch.  Each fd must be
// exclusively owned with exactly one in-flight request (the Python
// side falls back to per-branch calls when a remote repeats).
//
// results[i] mirrors raw_call's contract:
//   (True,  buf, att_size, dom_or_None, acks_or_None)   plain success
//   (False, buf, meta_size, None, acks_or_None)         full RpcMeta
//                                                       decode path
//   (None,  errkind, text, None, None)                  transport error
//       errkind: 1 = timeout, 2 = connection error, 3 = bad frame
// A failed branch never aborts the others.
static PyObject* scatter_call(PyObject*, PyObject* args) {
  PyObject* items;
  double timeout_s = -1.0;
  if (!PyArg_ParseTuple(args, "O|d", &items, &timeout_s)) return nullptr;
  PyObject* seq = PySequence_Fast(items, "items must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (n < 1 || n > 4096) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "bad scatter item count");
    return nullptr;
  }

  struct ScItem {
    int fd = -1;
    Py_buffer tail{}, payload{}, att{}, lead{};
    uint64_t cid = 0;
    char head[40];                 // TRPC hdr + cid TLV + att TLV
    size_t head_len = 0;
    char tmo[9];
    size_t tmo_len = 0;
    int err = 0;
    char errbuf[96] = {0};
    NativeBuf* out = nullptr;
    uint32_t meta = 0;
    std::vector<uint64_t> acks;
  };
  std::vector<ScItem> its((size_t)n);
  auto release_item = [](ScItem& it) {
    if (it.tail.obj) PyBuffer_Release(&it.tail);
    if (it.payload.obj) PyBuffer_Release(&it.payload);
    if (it.att.obj) PyBuffer_Release(&it.att);
    if (it.lead.obj) PyBuffer_Release(&it.lead);
    it.tail.obj = it.payload.obj = it.att.obj = it.lead.obj = nullptr;
  };
  auto release_all = [&]() {
    for (auto& it : its) {
      release_item(it);
      Py_XDECREF((PyObject*)it.out);
    }
    Py_DECREF(seq);
  };
  int timeout_ms = timeout_s >= 0 ? (int)(timeout_s * 1000) : 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    ScItem& it = its[(size_t)i];
    PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
    PyObject *att_obj = Py_None, *lead_obj = Py_None;
    unsigned long long cid = 0;
    if (!PyArg_ParseTuple(t, "iy*y*OKO", &it.fd, &it.tail, &it.payload,
                          &att_obj, &cid, &lead_obj)) {
      release_all();
      return nullptr;
    }
    it.cid = cid;
    if (att_obj != Py_None
        && PyObject_GetBuffer(att_obj, &it.att, PyBUF_SIMPLE) != 0) {
      release_all();
      return nullptr;
    }
    if (lead_obj != Py_None
        && PyObject_GetBuffer(lead_obj, &it.lead, PyBUF_SIMPLE) != 0) {
      release_all();
      return nullptr;
    }
    size_t alen = it.att.obj ? (size_t)it.att.len : 0;
    if ((size_t)it.payload.len + alen + (size_t)it.tail.len + 31
        > (size_t)kMaxBody) {
      release_all();
      PyErr_SetString(PyExc_ValueError,
                      "payload + attachment exceeds max body");
      return nullptr;
    }
    // same wire layout as raw_call's — single source in
    // build_request_head/build_tmo_tlv
    it.tmo_len = build_tmo_tlv(it.tmo, timeout_ms);
    it.head_len = build_request_head(it.head, it.cid, alen,
                                     (size_t)it.tail.len, it.tmo_len,
                                     (size_t)it.payload.len);
  }

  int64_t deadline = timeout_s >= 0 ? now_ms() + (int64_t)(timeout_s * 1000)
                                    : -1;
  // phase 1: scatter — write every branch's frame before reading any
  // response (per-branch errors recorded, the rest proceed)
  Py_BEGIN_ALLOW_THREADS;
  for (auto& it : its) {
    struct iovec iov[6];
    int ni = 0;
    if (it.lead.obj && it.lead.len > 0)
      iov[ni++] = {it.lead.buf, (size_t)it.lead.len};
    iov[ni++] = {it.head, it.head_len};
    if (it.tail.len > 0) iov[ni++] = {it.tail.buf, (size_t)it.tail.len};
    if (it.tmo_len) iov[ni++] = {it.tmo, it.tmo_len};
    if (it.payload.len > 0)
      iov[ni++] = {it.payload.buf, (size_t)it.payload.len};
    if (it.att.obj && it.att.len > 0)
      iov[ni++] = {it.att.buf, (size_t)it.att.len};
    it.err = write_all_iov(it.fd, iov, ni, deadline, it.errbuf,
                           sizeof it.errbuf);
  }
  Py_END_ALLOW_THREADS;

  // phase 2: gather — one response frame per fd (read_one_response
  // manages its own GIL transitions; entered with the GIL held)
  for (auto& it : its) {
    if (it.err) continue;
    it.err = read_one_response(it.fd, deadline, &it.out, &it.meta,
                               it.acks, it.errbuf, sizeof it.errbuf);
  }

  // phase 3: materialize per-item results (GIL held)
  PyObject* out_list = PyList_New(n);
  if (!out_list) {
    release_all();
    return nullptr;
  }
  bool fail = false;
  for (Py_ssize_t i = 0; i < n && !fail; i++) {
    ScItem& it = its[(size_t)i];
    PyObject* res = nullptr;
    if (it.err) {
      res = Py_BuildValue("(OisOO)", Py_None, it.err, it.errbuf,
                          Py_None, Py_None);
    } else {
      // scan the response meta exactly like raw_call: plain success
      // (cid/att/domain only, cid matching) unpacks here
      uint64_t rcid = 0;
      uint32_t ratt = 0;
      const char* dom = nullptr;
      uint32_t dom_len = 0;
      bool plain = scan_plain_resp(it.out->data, it.meta, &rcid, &ratt,
                                   &dom, &dom_len);
      PyObject* acks = Py_None;
      if (!it.acks.empty()) {
        acks = PyList_New((Py_ssize_t)it.acks.size());
        if (!acks) { fail = true; break; }
        for (size_t k = 0; k < it.acks.size(); k++)
          PyList_SET_ITEM(acks, (Py_ssize_t)k,
                          PyLong_FromUnsignedLongLong(it.acks[k]));
      } else {
        Py_INCREF(Py_None);
      }
      size_t blen = (size_t)it.out->size - it.meta;
      if (plain && rcid == it.cid && ratt <= blen) {
        PyObject* dom_obj;
        if (dom_len) {
          dom_obj = PyBytes_FromStringAndSize(dom, (Py_ssize_t)dom_len);
          if (!dom_obj) { Py_DECREF(acks); fail = true; break; }
        } else {
          dom_obj = Py_None;
          Py_INCREF(Py_None);
        }
        memmove(it.out->data, it.out->data + it.meta, blen);
        it.out->size = (Py_ssize_t)blen;
        res = Py_BuildValue("(ONkNN)", Py_True, (PyObject*)it.out,
                            (unsigned long)ratt, dom_obj, acks);
        it.out = nullptr;   // "N" consumed the reference either way —
                            // release_all must not decref it again
      } else {
        res = Py_BuildValue("(ONkON)", Py_False, (PyObject*)it.out,
                            (unsigned long)it.meta, Py_None, acks);
        it.out = nullptr;
      }
    }
    if (!res) { fail = true; break; }
    PyList_SET_ITEM(out_list, i, res);
  }
  release_all();
  if (fail) {
    Py_DECREF(out_list);
    return nullptr;
  }
  return out_list;
}


// sync_call_many(fd, parts, n, timeout_s) -> [(buf, meta_size), ...]
// Pipelined variant: write all parts (a batch of frames), then read
// exactly n TRPC frames.  One GIL release covers the whole batch write;
// reads release it per frame body.
static PyObject* sync_call_many(PyObject*, PyObject* args) {
  int fd;
  PyObject* parts;
  int expect;
  double timeout_s = -1.0;
  if (!PyArg_ParseTuple(args, "iOi|d", &fd, &parts, &expect, &timeout_s))
    return nullptr;
  if (expect < 1 || expect > (1 << 20)) {
    PyErr_SetString(PyExc_ValueError, "bad expect count");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(parts, "parts must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t nparts = PySequence_Fast_GET_SIZE(seq);
  std::vector<Py_buffer> views(nparts);
  Py_ssize_t nviews = 0;
  for (Py_ssize_t i = 0; i < nparts; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(item, &views[nviews], PyBUF_SIMPLE) != 0) {
      for (Py_ssize_t j = 0; j < nviews; j++) PyBuffer_Release(&views[j]);
      Py_DECREF(seq);
      return nullptr;
    }
    if (views[nviews].len > 0) nviews++;
    else PyBuffer_Release(&views[nviews]);
  }
  int64_t deadline = timeout_s >= 0 ? now_ms() + (int64_t)(timeout_s * 1000)
                                    : -1;
  int err = 0;
  char errbuf[96] = {0};

  // phase 1: write everything
  Py_BEGIN_ALLOW_THREADS;
  std::vector<struct iovec> iov(nviews);
  for (Py_ssize_t i = 0; i < nviews; i++) {
    iov[i].iov_base = views[i].buf;
    iov[i].iov_len = views[i].len;
  }
  size_t first = 0;
  while (first < (size_t)nviews && !err) {
    size_t cnt = (size_t)nviews - first;
    if (cnt > 64) cnt = 64;
    ssize_t w = writev(fd, iov.data() + first, (int)cnt);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int r = wait_fd(fd, POLLOUT, deadline);
        if (r == 0) err = 1;
        else if (r < 0) { err = 2; snprintf(errbuf, sizeof errbuf, "poll: %s", strerror(errno)); }
        continue;
      }
      if (errno == EINTR) continue;
      err = 2;
      snprintf(errbuf, sizeof errbuf, "write: %s", strerror(errno));
      break;
    }
    size_t left = (size_t)w;
    while (left > 0 && first < (size_t)nviews) {
      if (left >= iov[first].iov_len) {
        left -= iov[first].iov_len;
        first++;
      } else {
        iov[first].iov_base = (char*)iov[first].iov_base + left;
        iov[first].iov_len -= left;
        left = 0;
      }
    }
  }
  Py_END_ALLOW_THREADS;

  for (Py_ssize_t j = 0; j < nviews; j++) PyBuffer_Release(&views[j]);
  Py_DECREF(seq);
  if (err) goto fail;

  {
    // Read the WHOLE batch with the GIL released in one stretch: the
    // server's per-message Python dispatch then runs uncontended (GIL
    // ping-pong between reader and dispatcher is the dominant cost on
    // one core), and frames are sliced into NativeBufs afterwards under
    // a single GIL section.
    std::vector<char> acc;
    acc.reserve(1 << 20);
    std::vector<size_t> offs;       // start offsets of TRPC frames in acc
    offs.reserve((size_t)expect);
    std::vector<uint64_t> batch_acks;  // TICI ids interleaved in the batch
    size_t scanned = 0;   // prefix covered by complete frames
    int found = 0;
    Py_BEGIN_ALLOW_THREADS;
    while (found < expect && !err) {
      // scan newly complete frames (TICI credit-returns may interleave
      // when pipelined calls carry device descriptors — collect, skip)
      for (;;) {
        size_t avail = acc.size() - scanned;
        if (avail < 8) break;
        const char* p = acc.data() + scanned;
        if (memcmp(p, "TICI", 4) == 0) {
          uint32_t cnt = 0;
          memcpy(&cnt, p + 4, 4);
          size_t total = 8 + 8ul * cnt;
          if (cnt > 8000) {
            err = 3;
            snprintf(errbuf, sizeof errbuf, "oversized ack frame cnt=%u", cnt);
            break;
          }
          if (avail < total) break;
          for (uint32_t i = 0; i < cnt; i++) {
            uint64_t id;
            memcpy(&id, p + 8 + 8ul * i, 8);
            batch_acks.push_back(id);
          }
          scanned += total;
          continue;
        }
        if (avail < kHeaderSize) break;
        if (memcmp(p, "TRPC", 4) != 0) {
          err = 3;
          snprintf(errbuf, sizeof errbuf, "unexpected magic in batch read");
          break;
        }
        uint32_t body = 0, meta = 0;
        memcpy(&body, p + 4, 4);
        memcpy(&meta, p + 8, 4);
        (void)meta;
        if (body > kMaxBody || meta > body) {
          err = 3;
          snprintf(errbuf, sizeof errbuf, "bad frame sizes");
          break;
        }
        if (avail < kHeaderSize + (size_t)body) break;
        offs.push_back(scanned);
        scanned += kHeaderSize + body;
        if (++found >= expect) break;
      }
      if (err || found >= expect) break;
      char tmp[65536];
      ssize_t r = recv(fd, tmp, sizeof tmp, 0);
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        int pr = wait_fd(fd, POLLIN, deadline);
        if (pr == 0) err = 1;
        else if (pr < 0) { err = 2; snprintf(errbuf, sizeof errbuf, "poll: %s", strerror(errno)); }
        continue;
      }
      if (r == 0) { err = 2; snprintf(errbuf, sizeof errbuf, "connection closed by peer"); continue; }
      if (r < 0) {
        if (errno == EINTR) continue;
        err = 2;
        snprintf(errbuf, sizeof errbuf, "read: %s", strerror(errno));
        continue;
      }
      acc.insert(acc.end(), tmp, tmp + r);
    }
    // trailing bytes past the last expected response can only be TICI
    // credit-returns — drain to a frame boundary (a partial ack frame
    // left unread would desync the connection's next reader).  All
    // responses are in hand: grace the deadline for in-flight bytes.
    int64_t tdl = deadline;
    if (tdl >= 0) {
      int64_t grace = now_ms() + 2000;
      if (tdl < grace) tdl = grace;
    }
    while (!err && scanned < acc.size()) {
      size_t avail = acc.size() - scanned;
      const char* p = acc.data() + scanned;
      if (avail >= 4 && memcmp(p, "TICI", 4) != 0) {
        err = 3;
        snprintf(errbuf, sizeof errbuf, "unexpected trailing bytes in batch read");
        break;
      }
      if (avail >= 8) {
        uint32_t cnt = 0;
        memcpy(&cnt, p + 4, 4);
        if (cnt > 8000) {
          err = 3;
          snprintf(errbuf, sizeof errbuf, "oversized ack frame cnt=%u", cnt);
          break;
        }
        size_t total = 8 + 8ul * cnt;
        if (avail >= total) {
          for (uint32_t i = 0; i < cnt; i++) {
            uint64_t id;
            memcpy(&id, p + 8 + 8ul * i, 8);
            batch_acks.push_back(id);
          }
          scanned += total;
          continue;
        }
      }
      char tmp2[4096];
      ssize_t r = recv(fd, tmp2, sizeof tmp2, 0);
      if (r > 0) { acc.insert(acc.end(), tmp2, tmp2 + r); continue; }
      if (r == 0) { err = 2; snprintf(errbuf, sizeof errbuf, "connection closed mid-ack"); break; }
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int pr = wait_fd(fd, POLLIN, tdl);
        if (pr == 0) err = 1;
        else if (pr < 0) { err = 2; snprintf(errbuf, sizeof errbuf, "poll: %s", strerror(errno)); }
        continue;
      }
      if (errno == EINTR) continue;
      err = 2;
      snprintf(errbuf, sizeof errbuf, "read: %s", strerror(errno));
    }
    Py_END_ALLOW_THREADS;
    if (!err) {
      PyObject* out_list = PyList_New(expect);
      if (!out_list) return nullptr;
      for (int k = 0; k < expect; k++) {
        const char* p = acc.data() + offs[(size_t)k];
        uint32_t body = 0, meta = 0;
        memcpy(&body, p + 4, 4);
        memcpy(&meta, p + 8, 4);
        NativeBuf* b = nativebuf_new((Py_ssize_t)body);
        if (!b) { Py_DECREF(out_list); return nullptr; }
        memcpy(b->data, p + kHeaderSize, body);
        PyObject* tup = Py_BuildValue("(Nk)", (PyObject*)b,
                                      (unsigned long)meta);
        if (!tup) { Py_DECREF(out_list); return nullptr; }
        PyList_SET_ITEM(out_list, k, tup);
      }
      if (!batch_acks.empty()) {
        PyObject* acks = PyList_New((Py_ssize_t)batch_acks.size());
        if (!acks) { Py_DECREF(out_list); return nullptr; }
        for (size_t i = 0; i < batch_acks.size(); i++)
          PyList_SET_ITEM(acks, (Py_ssize_t)i,
                          PyLong_FromUnsignedLongLong(batch_acks[i]));
        return Py_BuildValue("(NN)", out_list, acks);
      }
      return out_list;
    }
  }
fail:
  if (err == 1)
    PyErr_SetString(PyExc_TimeoutError, "rpc deadline exceeded");
  else if (err == 2)
    PyErr_SetString(PyExc_ConnectionError, errbuf);
  else
    PyErr_SetString(PyExc_ValueError, errbuf);
  return nullptr;
}

// call_batch(fd, tail, payloads, timeout_s, cid_base, first_extra, lead)
//   -> (results, acks)
//
// The fully-native pipelined batch lane: frames are BUILT here (header +
// cid TLV + tail per payload, cids stamped cid_base..cid_base+n-1),
// written vectored, and the responses' metas are parsed here too — the
// whole batch costs Python ONE call.  tail = method/timeout TLVs shared
// by every frame; first_extra rides only frame 0's meta (auth);
// lead = raw bytes written before frame 0 (pending TICI ack flush).
//
// results[i] (matched by cid, so out-of-order servers are fine):
//   NativeBuf                — plain success payload, no attachment
//   (NativeBuf, meta_size)   — anything else (errors, attachments,
//                              descriptors): full frame body for
//                              Python's RpcMeta decode
static PyObject* call_batch(PyObject*, PyObject* args) {
  int fd;
  Py_buffer tail = {}, first_extra = {}, lead = {};
  PyObject* payloads;
  double timeout_s = -1.0;
  unsigned long long cid_base;
  if (!PyArg_ParseTuple(args, "iy*OdK|y*y*", &fd, &tail, &payloads,
                        &timeout_s, &cid_base, &first_extra, &lead)) {
    if (tail.obj) PyBuffer_Release(&tail);
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(payloads, "payloads must be a sequence");
  if (!seq) {
    PyBuffer_Release(&tail);
    if (first_extra.obj) PyBuffer_Release(&first_extra);
    if (lead.obj) PyBuffer_Release(&lead);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  auto cleanup_args = [&](std::vector<Py_buffer>& views) {
    for (auto& v : views) PyBuffer_Release(&v);
    PyBuffer_Release(&tail);
    if (first_extra.obj) PyBuffer_Release(&first_extra);
    if (lead.obj) PyBuffer_Release(&lead);
    Py_DECREF(seq);
  };
  std::vector<Py_buffer> views((size_t)n);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(item, &views[(size_t)i], PyBUF_SIMPLE) != 0) {
      views.resize((size_t)i);
      cleanup_args(views);
      return nullptr;
    }
    if ((size_t)views[(size_t)i].len > (size_t)kMaxBody) {
      // fail fast with a precise error instead of truncating the u32
      // header length and desyncing the stream (server would reject
      // anything past kMaxBody anyway)
      views.resize((size_t)i + 1);
      cleanup_args(views);
      PyErr_SetString(PyExc_ValueError, "batch payload exceeds max body");
      return nullptr;
    }
  }
  if (n == 0) {
    // still write `lead` (pending TICI acks the caller already dequeued
    // from its socket — dropping them would leak peer window credit)
    int lerr = 0;
    if (lead.obj && lead.len > 0) {
      Py_BEGIN_ALLOW_THREADS;
      const char* lp = (const char*)lead.buf;
      size_t left = (size_t)lead.len;
      int64_t dl = timeout_s >= 0 ? now_ms() + (int64_t)(timeout_s * 1000)
                                  : -1;
      while (left > 0 && !lerr) {
        ssize_t w = send(fd, lp, left, 0);
        if (w > 0) {
          lp += w;
          left -= (size_t)w;
          continue;
        }
        if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          if (wait_fd(fd, POLLOUT, dl) <= 0) lerr = 1;
          continue;
        }
        if (w < 0 && errno == EINTR) continue;
        lerr = 2;
      }
      Py_END_ALLOW_THREADS;
    }
    cleanup_args(views);
    if (lerr) {
      PyErr_SetString(lerr == 1 ? PyExc_TimeoutError : PyExc_ConnectionError,
                      "failed to flush pending acks");
      return nullptr;
    }
    return Py_BuildValue("(NN)", PyList_New(0), PyList_New(0));
  }
  if (n > (1 << 20)) {
    cleanup_args(views);
    PyErr_SetString(PyExc_ValueError, "batch too large");
    return nullptr;
  }

  int64_t deadline = timeout_s >= 0 ? now_ms() + (int64_t)(timeout_s * 1000)
                                    : -1;
  int err = 0;
  char errbuf[96] = {0};
  size_t tail_len = (size_t)tail.len;
  size_t extra_len = first_extra.obj ? (size_t)first_extra.len : 0;
  // per-frame arena chunk: 12B header + 13B cid TLV + tail (+extra on 0)
  const size_t kChunk = 25;
  std::vector<char> arena(n * (kChunk + tail_len) + extra_len);
  std::vector<struct iovec> iov;
  iov.reserve(2 * (size_t)n + 1);
  if (lead.obj && lead.len > 0)
    iov.push_back({lead.buf, (size_t)lead.len});
  std::vector<char> acc;                // response accumulator
  std::vector<size_t> offs((size_t)n, SIZE_MAX);  // body offset by index
  std::vector<uint32_t> osize((size_t)n, 0), ometa((size_t)n, 0);
  std::vector<uint64_t> batch_acks;

  Py_BEGIN_ALLOW_THREADS;
  // ---- build + write ----
  char* w = arena.data();
  for (Py_ssize_t i = 0; i < n; i++) {
    size_t ex = i == 0 ? extra_len : 0;
    uint32_t mlen = (uint32_t)(13 + ex + tail_len);
    uint32_t body = mlen + (uint32_t)views[(size_t)i].len;
    char* frame = w;
    memcpy(w, "TRPC", 4);
    memcpy(w + 4, &body, 4);
    memcpy(w + 8, &mlen, 4);
    w += 12;
    uint64_t cid = cid_base + (uint64_t)i;
    *w = 1;
    uint32_t l8 = 8;
    memcpy(w + 1, &l8, 4);
    memcpy(w + 5, &cid, 8);
    w += 13;
    if (ex) {
      memcpy(w, first_extra.buf, ex);
      w += ex;
    }
    if (tail_len) {
      memcpy(w, tail.buf, tail_len);
      w += tail_len;
    }
    iov.push_back({frame, (size_t)(w - frame)});
    if (views[(size_t)i].len > 0)
      iov.push_back({views[(size_t)i].buf, (size_t)views[(size_t)i].len});
  }
  size_t first = 0;
  while (first < iov.size() && !err) {
    size_t cnt = iov.size() - first;
    if (cnt > 64) cnt = 64;
    ssize_t wr = writev(fd, iov.data() + first, (int)cnt);
    if (wr < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int r = wait_fd(fd, POLLOUT, deadline);
        if (r == 0) err = 1;
        else if (r < 0) {
          err = 2;
          snprintf(errbuf, sizeof errbuf, "poll: %s", strerror(errno));
        }
        continue;
      }
      if (errno == EINTR) continue;
      err = 2;
      snprintf(errbuf, sizeof errbuf, "write: %s", strerror(errno));
      break;
    }
    size_t left = (size_t)wr;
    while (left > 0 && first < iov.size()) {
      if (left >= iov[first].iov_len) {
        left -= iov[first].iov_len;
        first++;
      } else {
        iov[first].iov_base = (char*)iov[first].iov_base + left;
        iov[first].iov_len -= left;
        left = 0;
      }
    }
  }

  // ---- read + scan n responses (TICI interleaves collected) ----
  if (!err) {
    acc.reserve(1 << 20);
    size_t scanned = 0;
    Py_ssize_t found = 0;
    while (found < n && !err) {
      for (;;) {
        size_t avail = acc.size() - scanned;
        if (avail < 8) break;
        const char* p = acc.data() + scanned;
        if (memcmp(p, "TICI", 4) == 0) {
          uint32_t cnt = 0;
          memcpy(&cnt, p + 4, 4);
          size_t total = 8 + 8ul * cnt;
          if (cnt > 8000) {
            err = 3;
            snprintf(errbuf, sizeof errbuf, "oversized ack frame");
            break;
          }
          if (avail < total) break;
          for (uint32_t i = 0; i < cnt; i++) {
            uint64_t id;
            memcpy(&id, p + 8 + 8ul * i, 8);
            batch_acks.push_back(id);
          }
          scanned += total;
          continue;
        }
        if (avail < kHeaderSize) break;
        if (memcmp(p, "TRPC", 4) != 0) {
          err = 3;
          snprintf(errbuf, sizeof errbuf, "unexpected magic in batch read");
          break;
        }
        uint32_t body = 0, meta = 0;
        memcpy(&body, p + 4, 4);
        memcpy(&meta, p + 8, 4);
        if (body > kMaxBody || meta > body) {
          err = 3;
          snprintf(errbuf, sizeof errbuf, "bad frame sizes");
          break;
        }
        if (avail < kHeaderSize + (size_t)body) break;
        // place by cid (servers running handlers on fibers may answer
        // out of order)
        uint64_t rcid = 0;
        {
          // response metas reuse the TLV walk; only cid placement needs
          // to succeed here — full decode stays in Python when unusual
          size_t off2 = 0;
          bool got_cid = false;
          const char* mp = p + kHeaderSize;
          while (off2 + 5 <= meta) {
            uint8_t tag = (uint8_t)mp[off2];
            uint32_t ln;
            memcpy(&ln, mp + off2 + 1, 4);
            off2 += 5;
            if (off2 + ln > meta) break;
            if (tag == 1 && ln == 8) {
              memcpy(&rcid, mp + off2, 8);
              got_cid = true;
            }
            off2 += ln;
          }
          if (!got_cid) {
            err = 3;
            snprintf(errbuf, sizeof errbuf,
                     "batch response missing correlation id");
            break;
          }
        }
        if (rcid < cid_base || rcid >= cid_base + (uint64_t)n
            || offs[(size_t)(rcid - cid_base)] != SIZE_MAX) {
          err = 3;
          snprintf(errbuf, sizeof errbuf,
                   "batch response cid out of range");
          break;
        }
        size_t idx = (size_t)(rcid - cid_base);
        offs[idx] = scanned + kHeaderSize;
        osize[idx] = body;
        ometa[idx] = meta;
        scanned += kHeaderSize + body;
        found++;
      }
      if (err || found >= n) break;
      char tmp[65536];
      ssize_t r = recv(fd, tmp, sizeof tmp, 0);
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        int pr = wait_fd(fd, POLLIN, deadline);
        if (pr == 0) err = 1;
        else if (pr < 0) {
          err = 2;
          snprintf(errbuf, sizeof errbuf, "poll: %s", strerror(errno));
        }
        continue;
      }
      if (r == 0) {
        err = 2;
        snprintf(errbuf, sizeof errbuf, "connection closed by peer");
        continue;
      }
      if (r < 0) {
        if (errno == EINTR) continue;
        err = 2;
        snprintf(errbuf, sizeof errbuf, "read: %s", strerror(errno));
        continue;
      }
      acc.insert(acc.end(), tmp, tmp + r);
    }
    // drain trailing TICI frames to a boundary (grace past deadline:
    // every response is already in hand)
    int64_t tdl = deadline;
    if (tdl >= 0) {
      int64_t grace = now_ms() + 2000;
      if (tdl < grace) tdl = grace;
    }
    while (!err && scanned < acc.size()) {
      size_t avail = acc.size() - scanned;
      const char* p = acc.data() + scanned;
      if (avail >= 4 && memcmp(p, "TICI", 4) != 0) {
        err = 3;
        snprintf(errbuf, sizeof errbuf,
                 "unexpected trailing bytes in batch read");
        break;
      }
      if (avail >= 8) {
        uint32_t cnt = 0;
        memcpy(&cnt, p + 4, 4);
        if (cnt > 8000) {
          err = 3;
          snprintf(errbuf, sizeof errbuf, "oversized ack frame");
          break;
        }
        size_t total = 8 + 8ul * cnt;
        if (avail >= total) {
          for (uint32_t i = 0; i < cnt; i++) {
            uint64_t id;
            memcpy(&id, p + 8 + 8ul * i, 8);
            batch_acks.push_back(id);
          }
          scanned += total;
          continue;
        }
      }
      char tmp2[4096];
      ssize_t r = recv(fd, tmp2, sizeof tmp2, 0);
      if (r > 0) {
        acc.insert(acc.end(), tmp2, tmp2 + r);
        continue;
      }
      if (r == 0) {
        err = 2;
        snprintf(errbuf, sizeof errbuf, "connection closed mid-ack");
        break;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int pr = wait_fd(fd, POLLIN, tdl);
        if (pr == 0) err = 1;
        else if (pr < 0) {
          err = 2;
          snprintf(errbuf, sizeof errbuf, "poll: %s", strerror(errno));
        }
        continue;
      }
      if (errno == EINTR) continue;
      err = 2;
      snprintf(errbuf, sizeof errbuf, "read: %s", strerror(errno));
    }
  }
  Py_END_ALLOW_THREADS;

  cleanup_args(views);
  if (err) {
    if (err == 1)
      PyErr_SetString(PyExc_TimeoutError, "rpc deadline exceeded");
    else if (err == 2)
      PyErr_SetString(PyExc_ConnectionError, errbuf);
    else
      PyErr_SetString(PyExc_ValueError, errbuf);
    return nullptr;
  }

  // ---- materialize results (GIL held) ----
  PyObject* out_list = PyList_New(n);
  if (!out_list) return nullptr;
  for (Py_ssize_t k = 0; k < n; k++) {
    const char* bp = acc.data() + offs[(size_t)k];
    uint32_t body = osize[(size_t)k], meta = ometa[(size_t)k];
    // classify: plain success (only cid/att/domain tags, att==0) gets a
    // bare payload buffer; everything else goes back whole for RpcMeta
    bool plain = true;
    uint32_t att = 0;
    {
      size_t off2 = 0;
      while (off2 + 5 <= meta) {
        uint8_t tag = (uint8_t)bp[off2];
        uint32_t ln;
        memcpy(&ln, bp + off2 + 1, 4);
        off2 += 5;
        if (off2 + ln > meta) {
          plain = false;
          break;
        }
        if (tag == 3 && ln == 4) memcpy(&att, bp + off2, 4);
        else if (tag != 1 && tag != 15) plain = false;
        off2 += ln;
      }
    }
    PyObject* item;
    if (plain && att == 0) {
      NativeBuf* b = nativebuf_new((Py_ssize_t)(body - meta));
      if (!b) {
        Py_DECREF(out_list);
        return nullptr;
      }
      memcpy(b->data, bp + meta, body - meta);
      item = (PyObject*)b;
    } else {
      NativeBuf* b = nativebuf_new((Py_ssize_t)body);
      if (!b) {
        Py_DECREF(out_list);
        return nullptr;
      }
      memcpy(b->data, bp, body);
      item = Py_BuildValue("(Nk)", (PyObject*)b, (unsigned long)meta);
      if (!item) {
        Py_DECREF(out_list);
        return nullptr;
      }
    }
    PyList_SET_ITEM(out_list, k, item);
  }
  PyObject* acks = PyList_New((Py_ssize_t)batch_acks.size());
  if (!acks) {
    Py_DECREF(out_list);
    return nullptr;
  }
  for (size_t i = 0; i < batch_acks.size(); i++)
    PyList_SET_ITEM(acks, (Py_ssize_t)i,
                    PyLong_FromUnsignedLongLong(batch_acks[i]));
  return Py_BuildValue("(NN)", out_list, acks);
}

// ---------------------------------------------------------------------------
// ClientDemux — the native CLIENT completion lane (the client-side twin
// of the server's kind-3 slim lane).  The full-Controller async path
// used to pay, per response: one dispatcher wakeup, a fiber spawn, a
// Python frame cut, a full RpcMeta decode and a dict lookup.  Here a
// dedicated epoll loop owns the read side of attached client sockets,
// parses response frames off the read burst in C++, correlates them by
// cid against a native in-flight table (registered at send time from
// controller._issue_rpc), and delivers a whole burst of completions to
// Python in ONE batched callback:
//
//     callback(token, status, completions, fallbacks, acks)
//
//     status       0 = burst, 1 = peer EOF, 2 = transport/protocol error
//     completions  [(cid, payload_buf, att_size, dom_or_None), ...] —
//                  PLAIN success responses only (cid/att/ici-domain
//                  meta tags), payload_buf = NativeBuf(payload ++ att)
//     fallbacks    [(reason, raw_frame_buf), ...] — anything the scan
//                  cannot resolve natively, delivered as the EXACT wire
//                  bytes (header included) for the classic Python demux
//                  (byte-identical by construction).  ``reason`` indexes
//                  the closed CliFb enum below — no "unknown" bucket.
//     acks         TICI credit-return ids interleaved in the burst
//
// The in-flight table is the rendezvous: expect(token, cid) BEFORE the
// request write, cancel(token, cid) at call end (mirrors the Python
// socket's add_inflight/remove_inflight, which stays authoritative for
// failure notification).  A response whose meta carries anything
// controller-tier (errors, compression, shm, descriptors, stream
// grants) keeps its table entry and falls back whole — the classic
// path completes it and call teardown cancels the entry.
// ---------------------------------------------------------------------------

// closed client-lane fallback reason enum (mirrors FbReason's
// discipline: every frame routed OFF the native demux increments
// exactly one of these).  CONTRACT (machine-checked): kCliFbNames and
// client_lane.REASONS must track this enum — tools/check gates both.
enum CliFb : int {
  CFB_UNKNOWN_CID = 0,   // cid not in the in-flight table (stale /
                         // cancelled / foreign response)
  CFB_META_UNPARSED,     // no cid tag found / malformed meta walk
  CFB_META_TAGS,         // controller-tier response meta (error codes,
                         // compression, shm, descriptors, stream
                         // grants): full RpcMeta decode in Python
  CFB_STREAM_FRAME,      // TSTR stream frame on a lane socket
  CFB_UNKNOWN_MAGIC,     // not TRPC/TICI/TSTR: sticky passthrough —
                         // the Python protocol registry owns the conn
  CFB_REASONS
};
static const char* kCliFbNames[CFB_REASONS] = {
    "cli_unknown_cid", "cli_meta_unparsed", "cli_meta_tags",
    "cli_stream_frame", "cli_unknown_magic",
};

struct CliConn {
  int fd = -1;            // demux-owned dup() of the Python socket's fd
                          // (a Python-side close can never strand a
                          // recv on a reused fd number)
  uint64_t token = 0;
  bool dead = false;      // detach() marks; only the loop frees
  bool passthrough = false;  // unknown magic seen: forward everything
  std::string acc;        // unconsumed wire bytes across reads
  std::unordered_set<uint64_t> inflight;  // guarded by DemuxImpl::mu
};

struct CliTelemetry {
  uint64_t completions = 0;      // natively-demuxed responses
  uint64_t fallbacks[CFB_REASONS] = {};
  uint64_t acks = 0;
  uint64_t bursts = 0;           // batched callbacks delivered
  uint64_t bytes_in = 0;
  Hist comp_burst;               // completions per batched callback
};

struct DemuxImpl {
  PyObject* callback = nullptr;
  int epfd = -1;
  int wakefd = -1;
  std::atomic<bool> stopping{false};
  std::atomic<bool> running{false};
  // one mutex guards the conn map, every conn's inflight set and the
  // reap list: expect/cancel are sub-microsecond ops from GIL-holding
  // issuer threads, the loop touches the tables only around lookups
  std::mutex mu;
  std::unordered_map<uint64_t, CliConn*> conns;
  std::vector<uint64_t> reap;
  CliTelemetry tel;              // loop-thread writes; racy reads OK
};

// tokens are PROCESS-unique, not per-demux: the client lane runs a
// POOL of demux loops (one per core-ish, client_lane.py), and the
// Python routing tables key on the bare token — two loops handing out
// overlapping counters would cross-wire sockets
static std::atomic<uint64_t> g_cli_token{1};

typedef struct {
  PyObject_HEAD DemuxImpl* d;
} DemuxObj;

static void demux_wake(DemuxImpl* d) {
  uint64_t one = 1;
  ssize_t r = write(d->wakefd, &one, 8);
  (void)r;
}

// one parsed completion / fallback span into CliConn::acc
struct CliComp {
  uint64_t cid;
  size_t pay_off, pay_len;
  uint32_t att;
  size_t dom_off;
  uint32_t dom_len;
};
struct CliFbSpan {
  int reason;
  size_t off, len;
};

// Parse as many complete frames as possible from c->acc starting at 0;
// classifies each against the in-flight table.  Returns consumed bytes;
// *hard_err set on protocol-fatal framing (bad sizes).  Runs on the
// loop thread WITHOUT the GIL; takes d->mu only around table lookups.
static size_t cli_parse(DemuxImpl* d, CliConn* c,
                        std::vector<CliComp>& comps,
                        std::vector<CliFbSpan>& fbs,
                        std::vector<uint64_t>& acks, bool* hard_err) {
  const std::string& a = c->acc;
  size_t off = 0;
  while (a.size() - off >= 4) {
    const char* p = a.data() + off;
    size_t avail = a.size() - off;
    if (c->passthrough) {
      fbs.push_back({CFB_UNKNOWN_MAGIC, off, avail});
      off = a.size();
      break;
    }
    if (memcmp(p, "TICI", 4) == 0) {
      if (avail < 8) break;
      uint32_t cnt = 0;
      memcpy(&cnt, p + 4, 4);
      if (cnt > (1u << 20)) {
        *hard_err = true;
        break;
      }
      size_t total = 8 + 8ul * cnt;
      if (avail < total) break;
      for (uint32_t i = 0; i < cnt; i++) {
        uint64_t id;
        memcpy(&id, p + 8 + 8ul * i, 8);
        acks.push_back(id);
      }
      off += total;
      continue;
    }
    if (memcmp(p, "TRPC", 4) == 0) {
      if (avail < kHeaderSize) break;
      uint32_t body = 0, meta = 0;
      memcpy(&body, p + 4, 4);
      memcpy(&meta, p + 8, 4);
      if (body > kMaxBody || meta > body) {
        *hard_err = true;
        break;
      }
      size_t total = kHeaderSize + (size_t)body;
      if (avail < total) break;
      // response meta walk: cid + plain-success classification (the
      // same shape scan_plain_resp applies on the blocking lanes)
      uint64_t cid = 0;
      bool got_cid = false, plain = true;
      uint32_t att = 0;
      size_t dom_off = 0;
      uint32_t dom_len = 0;
      const char* mp = p + kHeaderSize;
      size_t mo = 0;
      while (mo + 5 <= meta) {
        uint8_t tag = (uint8_t)mp[mo];
        uint32_t ln;
        memcpy(&ln, mp + mo + 1, 4);
        mo += 5;
        if (mo + ln > meta) {
          got_cid = false;       // malformed walk: meta_unparsed
          break;
        }
        if (tag == 1 && ln == 8) {
          memcpy(&cid, mp + mo, 8);
          got_cid = true;
        } else if (tag == 3 && ln == 4) {
          memcpy(&att, mp + mo, 4);
        } else if (tag == 15) {
          dom_off = off + kHeaderSize + mo;
          dom_len = ln;
        } else {
          plain = false;
        }
        mo += ln;
      }
      if (!got_cid) {
        fbs.push_back({CFB_META_UNPARSED, off, total});
        off += total;
        continue;
      }
      bool eligible = plain && (size_t)att <= (size_t)body - meta;
      bool known, taken = false;
      {
        std::lock_guard<std::mutex> g(d->mu);
        known = c->inflight.count(cid) != 0;
        if (known && eligible) {
          c->inflight.erase(cid);
          taken = true;
        }
        // non-eligible shapes keep their entry: the classic demux
        // completes them and call teardown cancels the table row
      }
      if (taken) {
        comps.push_back({cid, off + kHeaderSize + meta,
                         (size_t)body - meta, att, dom_off, dom_len});
      } else if (!known) {
        fbs.push_back({CFB_UNKNOWN_CID, off, total});
      } else {
        fbs.push_back({CFB_META_TAGS, off, total});
      }
      off += total;
      continue;
    }
    if (memcmp(p, "TSTR", 4) == 0) {
      if (avail < 17) break;
      uint32_t len = 0;
      memcpy(&len, p + 13, 4);
      if (len > kMaxBody) {
        *hard_err = true;
        break;
      }
      size_t total = 4 + 13 + (size_t)len;
      if (avail < total) break;
      fbs.push_back({CFB_STREAM_FRAME, off, total});
      off += total;
      continue;
    }
    // unknown magic: STICKY passthrough — from here on every byte of
    // this connection belongs to the Python protocol registry (the
    // Python side detaches and converts to dispatcher reads)
    c->passthrough = true;
    fbs.push_back({CFB_UNKNOWN_MAGIC, off, avail});
    off = a.size();
    break;
  }
  return off;
}

// deliver one batched callback (ONE GIL entry per read burst) — the
// client-side mirror of flush_py_batch's discipline
static void cli_deliver(DemuxImpl* d, CliConn* c, int status,
                        std::vector<CliComp>& comps,
                        std::vector<CliFbSpan>& fbs,
                        std::vector<uint64_t>& acks) {
  if (status == 0 && comps.empty() && fbs.empty() && acks.empty())
    return;
  const std::string& a = c->acc;
  PyGILState_STATE gs = PyGILState_Ensure();
  PyObject* pc = Py_None;
  PyObject* pf = Py_None;
  PyObject* pa = Py_None;
  bool ok = true;
  if (!comps.empty()) {
    pc = PyList_New((Py_ssize_t)comps.size());
    ok = pc != nullptr;
    for (size_t i = 0; ok && i < comps.size(); i++) {
      CliComp& cm = comps[i];
      NativeBuf* b = nativebuf_new((Py_ssize_t)cm.pay_len);
      if (!b) {
        ok = false;
        break;
      }
      if (cm.pay_len) memcpy(b->data, a.data() + cm.pay_off, cm.pay_len);
      PyObject* dom;
      if (cm.dom_len) {
        dom = PyBytes_FromStringAndSize(a.data() + cm.dom_off,
                                        (Py_ssize_t)cm.dom_len);
        if (!dom) {
          Py_DECREF((PyObject*)b);
          ok = false;
          break;
        }
      } else {
        dom = Py_None;
        Py_INCREF(Py_None);
      }
      PyObject* t = Py_BuildValue("(KNkN)", (unsigned long long)cm.cid,
                                  (PyObject*)b, (unsigned long)cm.att,
                                  dom);
      if (!t) {
        ok = false;
        break;
      }
      PyList_SET_ITEM(pc, (Py_ssize_t)i, t);
    }
  }
  if (ok && !fbs.empty()) {
    pf = PyList_New((Py_ssize_t)fbs.size());
    ok = pf != nullptr;
    for (size_t i = 0; ok && i < fbs.size(); i++) {
      CliFbSpan& f = fbs[i];
      NativeBuf* b = nativebuf_new((Py_ssize_t)f.len);
      if (!b) {
        ok = false;
        break;
      }
      if (f.len) memcpy(b->data, a.data() + f.off, f.len);
      PyObject* t = Py_BuildValue("(iN)", f.reason, (PyObject*)b);
      if (!t) {
        ok = false;
        break;
      }
      PyList_SET_ITEM(pf, (Py_ssize_t)i, t);
    }
  }
  if (ok && !acks.empty()) {
    pa = PyList_New((Py_ssize_t)acks.size());
    ok = pa != nullptr;
    for (size_t i = 0; ok && i < acks.size(); i++) {
      PyObject* v = PyLong_FromUnsignedLongLong(acks[i]);
      if (!v) {
        ok = false;
        break;
      }
      PyList_SET_ITEM(pa, (Py_ssize_t)i, v);
    }
  }
  if (ok) {
    d->tel.bursts++;
    d->tel.completions += comps.size();
    d->tel.comp_burst.add((uint64_t)comps.size());
    for (auto& f : fbs) d->tel.fallbacks[f.reason]++;
    d->tel.acks += acks.size();
    PyObject* r = PyObject_CallFunction(
        d->callback, "KiOOO", (unsigned long long)c->token, status,
        pc == nullptr ? Py_None : pc, pf == nullptr ? Py_None : pf,
        pa == nullptr ? Py_None : pa);
    if (!r)
      PyErr_WriteUnraisable(d->callback);
    else
      Py_DECREF(r);
  } else {
    PyErr_WriteUnraisable(d->callback);
  }
  if (pc != Py_None) Py_XDECREF(pc);
  if (pf != Py_None) Py_XDECREF(pf);
  if (pa != Py_None) Py_XDECREF(pa);
  PyGILState_Release(gs);
}

// one readable event on a lane conn: drain the socket, parse, deliver
static void cli_readable(DemuxImpl* d, CliConn* c) {
  int status = 0;
  for (;;) {
    char tmp[65536];
    ssize_t r = recv(c->fd, tmp, sizeof tmp, 0);
    if (r > 0) {
      c->acc.append(tmp, (size_t)r);
      d->tel.bytes_in += (uint64_t)r;
      // bound one burst's accumulation; level-triggered epoll re-fires
      // for whatever the kernel still holds
      if (c->acc.size() >= (8u << 20)) break;
      continue;
    }
    if (r == 0) {
      status = 1;                       // peer EOF
      break;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) break;
    if (errno == EINTR) continue;
    status = 2;                         // transport error
    break;
  }
  std::vector<CliComp> comps;
  std::vector<CliFbSpan> fbs;
  std::vector<uint64_t> acks;
  bool hard_err = false;
  size_t used = cli_parse(d, c, comps, fbs, acks, &hard_err);
  if (hard_err && status == 0) status = 2;   // bad framing: fail conn
  cli_deliver(d, c, status, comps, fbs, acks);
  c->acc.erase(0, used);
  if (status != 0) {
    // stop polling a dying conn; the Python side detaches (reap frees)
    c->dead = true;
    epoll_ctl(d->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  }
}

static void demux_run(DemuxImpl* d) {
  struct epoll_event evs[64];
  while (!d->stopping.load()) {
    int n = epoll_wait(d->epfd, evs, 64, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    // reap detached conns (only the loop frees — an issuer thread must
    // never pull a CliConn out from under a recv)
    {
      std::vector<CliConn*> gone;
      {
        std::lock_guard<std::mutex> g(d->mu);
        for (uint64_t tok : d->reap) {
          auto it = d->conns.find(tok);
          if (it == d->conns.end()) continue;
          gone.push_back(it->second);
          d->conns.erase(it);
        }
        d->reap.clear();
      }
      for (CliConn* c : gone) {
        close(c->fd);
        delete c;
      }
    }
    for (int i = 0; i < n; i++) {
      uint64_t tok = evs[i].data.u64;
      if (tok == 0) {
        uint64_t drain;
        while (read(d->wakefd, &drain, 8) > 0) {
        }
        continue;
      }
      CliConn* c = nullptr;
      {
        std::lock_guard<std::mutex> g(d->mu);
        auto it = d->conns.find(tok);
        if (it != d->conns.end() && !it->second->dead) c = it->second;
      }
      if (c == nullptr) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        // drain what the kernel still holds first (a peer close right
        // after the last response must deliver that response)
        cli_readable(d, c);
        if (!c->dead) {
          std::vector<CliComp> e1;
          std::vector<CliFbSpan> e2;
          std::vector<uint64_t> e3;
          cli_deliver(d, c, 1, e1, e2, e3);
          c->dead = true;
          epoll_ctl(d->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
        }
        continue;
      }
      if (evs[i].events & EPOLLIN) cli_readable(d, c);
    }
  }
  d->running.store(false);
}

static PyObject* Demux_new(PyTypeObject* type, PyObject* args,
                           PyObject* kwds) {
  PyObject* callback;
  static const char* kwlist[] = {"callback", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "O", (char**)kwlist,
                                   &callback))
    return nullptr;
  if (!PyCallable_Check(callback)) {
    PyErr_SetString(PyExc_TypeError, "callback must be callable");
    return nullptr;
  }
  DemuxObj* self = (DemuxObj*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->d = new DemuxImpl();
  Py_INCREF(callback);
  self->d->callback = callback;
  self->d->epfd = epoll_create1(EPOLL_CLOEXEC);
  self->d->wakefd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = 0;
  epoll_ctl(self->d->epfd, EPOLL_CTL_ADD, self->d->wakefd, &ev);
  return (PyObject*)self;
}

// run_loop() — the demux loop body, called from a Python thread (its
// resident frame pins the datastack chunk, so per-burst callbacks skip
// the cold-eval mmap churn a C thread pays).  Blocks until stop().
static PyObject* Demux_run_loop(DemuxObj* self, PyObject*) {
  DemuxImpl* d = self->d;
  d->running.store(true);
  Py_BEGIN_ALLOW_THREADS;
  demux_run(d);
  Py_END_ALLOW_THREADS;
  Py_RETURN_NONE;
}

// attach(fd) -> token.  The demux dup()s the fd: reads belong to the
// lane from here on (the Python socket keeps the write side).  The fd
// is NOT armed yet — the caller finishes its token -> socket
// bookkeeping first and then calls arm(token), so the very first
// burst/EOF callback can never race the registration and be dropped.
static PyObject* Demux_attach(DemuxObj* self, PyObject* args) {
  int fd;
  if (!PyArg_ParseTuple(args, "i", &fd)) return nullptr;
  DemuxImpl* d = self->d;
  int dupfd = dup(fd);
  if (dupfd < 0) {
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  CliConn* c = new CliConn();
  c->fd = dupfd;
  c->token = g_cli_token++;
  {
    std::lock_guard<std::mutex> g(d->mu);
    d->conns[c->token] = c;
  }
  return PyLong_FromUnsignedLongLong(c->token);
}

// arm(token) -> bool: register the attached fd with epoll (reads start
// flowing).  Call AFTER the Python-side routing state is in place.
static PyObject* Demux_arm(DemuxObj* self, PyObject* args) {
  unsigned long long token;
  if (!PyArg_ParseTuple(args, "K", &token)) return nullptr;
  DemuxImpl* d = self->d;
  CliConn* c = nullptr;
  {
    std::lock_guard<std::mutex> g(d->mu);
    auto it = d->conns.find(token);
    if (it != d->conns.end() && !it->second->dead) c = it->second;
  }
  if (c == nullptr) Py_RETURN_FALSE;
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = c->token;
  if (epoll_ctl(d->epfd, EPOLL_CTL_ADD, c->fd, &ev) != 0)
    Py_RETURN_FALSE;
  Py_RETURN_TRUE;
}

static PyObject* Demux_detach(DemuxObj* self, PyObject* args) {
  unsigned long long token;
  if (!PyArg_ParseTuple(args, "K", &token)) return nullptr;
  DemuxImpl* d = self->d;
  {
    std::lock_guard<std::mutex> g(d->mu);
    auto it = d->conns.find(token);
    if (it != d->conns.end()) {
      it->second->dead = true;
      epoll_ctl(d->epfd, EPOLL_CTL_DEL, it->second->fd, nullptr);
      d->reap.push_back(token);
    }
  }
  if (d->running.load())
    demux_wake(d);
  else {
    // loop not running (teardown order): reap inline
    std::vector<CliConn*> gone;
    {
      std::lock_guard<std::mutex> g(d->mu);
      for (uint64_t tok : d->reap) {
        auto it = d->conns.find(tok);
        if (it == d->conns.end()) continue;
        gone.push_back(it->second);
        d->conns.erase(it);
      }
      d->reap.clear();
    }
    for (CliConn* c : gone) {
      close(c->fd);
      delete c;
    }
  }
  Py_RETURN_NONE;
}

// expect(token, cid) -> bool: register one in-flight correlation id
// BEFORE the request write (a response racing the registration would
// otherwise demux as unknown_cid)
static PyObject* Demux_expect(DemuxObj* self, PyObject* args) {
  unsigned long long token, cid;
  if (!PyArg_ParseTuple(args, "KK", &token, &cid)) return nullptr;
  DemuxImpl* d = self->d;
  std::lock_guard<std::mutex> g(d->mu);
  auto it = d->conns.find(token);
  if (it == d->conns.end() || it->second->dead) Py_RETURN_FALSE;
  it->second->inflight.insert(cid);
  Py_RETURN_TRUE;
}

// cancel(token, cid) -> bool: drop a registration (call teardown);
// True when the entry was still present
static PyObject* Demux_cancel(DemuxObj* self, PyObject* args) {
  unsigned long long token, cid;
  if (!PyArg_ParseTuple(args, "KK", &token, &cid)) return nullptr;
  DemuxImpl* d = self->d;
  std::lock_guard<std::mutex> g(d->mu);
  auto it = d->conns.find(token);
  if (it == d->conns.end()) Py_RETURN_FALSE;
  if (it->second->inflight.erase(cid)) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

static PyObject* Demux_stop(DemuxObj* self, PyObject*) {
  self->d->stopping.store(true);
  demux_wake(self->d);
  Py_RETURN_NONE;
}

// telemetry() -> the client lane's observability table (same racy-read
// discipline as Engine.telemetry)
static PyObject* Demux_telemetry(DemuxObj* self, PyObject*) {
  DemuxImpl* d = self->d;
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  PyObject* fbd = PyDict_New();
  bool ok = fbd != nullptr;
  uint64_t fb_total = 0;
  for (int i = 0; ok && i < CFB_REASONS; i++) {
    fb_total += d->tel.fallbacks[i];
    ok = set_u64(fbd, kCliFbNames[i], d->tel.fallbacks[i]) == 0;
  }
  if (ok) ok = PyDict_SetItemString(out, "fallbacks", fbd) == 0;
  Py_XDECREF(fbd);
  if (ok) ok = set_u64(out, "completions", d->tel.completions) == 0;
  if (ok) ok = set_u64(out, "fallback_total", fb_total) == 0;
  if (ok) ok = set_u64(out, "acks", d->tel.acks) == 0;
  if (ok) ok = set_u64(out, "bursts", d->tel.bursts) == 0;
  if (ok) ok = set_u64(out, "bytes_in", d->tel.bytes_in) == 0;
  if (ok) ok = set_hist(out, "comp_burst", d->tel.comp_burst) == 0;
  if (ok) {
    size_t n;
    {
      std::lock_guard<std::mutex> g(d->mu);
      n = d->conns.size();
    }
    ok = set_u64(out, "attached", (uint64_t)n) == 0;
  }
  if (!ok) {
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

// pending() — total in-flight entries still registered across every
// attached conn: the drain plane waits for 0 before process exit (a
// leftover entry is a response the table would deliver into a torn-
// down Python world).
static PyObject* Demux_pending(DemuxObj* self, PyObject* args) {
  (void)args;
  size_t n = 0;
  {
    std::lock_guard<std::mutex> g(self->d->mu);
    for (auto& kv : self->d->conns) n += kv.second->inflight.size();
  }
  return PyLong_FromSize_t(n);
}

static void Demux_dealloc(DemuxObj* self) {
  if (self->d) {
    self->d->stopping.store(true);
    demux_wake(self->d);
    // give a still-running loop a moment to exit (the bridge joins its
    // thread before dropping the object; this is belt-and-braces)
    Py_BEGIN_ALLOW_THREADS;
    for (int i = 0; i < 100 && self->d->running.load(); i++) {
      struct timespec ts{0, 10 * 1000 * 1000};
      nanosleep(&ts, nullptr);
    }
    Py_END_ALLOW_THREADS;
    for (auto& kv : self->d->conns) {
      close(kv.second->fd);
      delete kv.second;
    }
    close(self->d->epfd);
    close(self->d->wakefd);
    Py_XDECREF(self->d->callback);
    delete self->d;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static PyMethodDef Demux_methods[] = {
    {"run_loop", (PyCFunction)Demux_run_loop, METH_NOARGS,
     "run the demux loop on the calling (Python) thread until stop()"},
    {"attach", (PyCFunction)Demux_attach, METH_VARARGS,
     "attach(fd) -> token: the lane dup()s and owns the read side "
     "(unarmed until arm(token))"},
    {"arm", (PyCFunction)Demux_arm, METH_VARARGS,
     "arm(token) -> bool: start demuxing an attached fd (call after "
     "the caller's token routing is in place)"},
    {"detach", (PyCFunction)Demux_detach, METH_VARARGS,
     "detach(token): stop demuxing; the dup'd fd closes on the loop"},
    {"expect", (PyCFunction)Demux_expect, METH_VARARGS,
     "expect(token, cid) -> bool: register an in-flight response"},
    {"cancel", (PyCFunction)Demux_cancel, METH_VARARGS,
     "cancel(token, cid) -> bool: drop a registration at call end"},
    {"stop", (PyCFunction)Demux_stop, METH_NOARGS, nullptr},
    {"pending", (PyCFunction)Demux_pending, METH_NOARGS,
     "pending() -> int: in-flight entries across attached conns (the "
     "drain plane waits for 0)"},
    {"telemetry", (PyCFunction)Demux_telemetry, METH_NOARGS,
     "client-lane counters: completions, reason-coded fallbacks, "
     "completions-per-burst histogram, acks, attached conns"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject DemuxType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

static PyMethodDef module_methods[] = {
    {"sync_call", (PyCFunction)sync_call, METH_VARARGS,
     "sync_call(fd, parts, timeout_s) -> (buf, meta_size): write request "
     "parts, read one TRPC frame, GIL released"},
    {"sync_call_many", (PyCFunction)sync_call_many, METH_VARARGS,
     "sync_call_many(fd, parts, expect, timeout_s) -> [(buf, meta_size)]: "
     "pipelined batch — write all frames, read expect responses"},
    {"call_batch", (PyCFunction)call_batch, METH_VARARGS,
     "call_batch(fd, tail, payloads, timeout_s, cid_base, first_extra, "
     "lead) -> (results, acks): build/write/read a whole pipelined batch "
     "natively; results matched by correlation id"},
    {"raw_call", (PyCFunction)raw_call, METH_VARARGS,
     "raw_call(fd, tail, payload, attachment, timeout_ms, cid, lead) -> "
     "(ok, buf, n, dom, acks): one raw-lane round trip fully native — "
     "frame built, written, read and meta-scanned in C++"},
    {"scatter_call", (PyCFunction)scatter_call, METH_VARARGS,
     "scatter_call(items, timeout_s) -> [per-item result]: fan-out fast "
     "lane — write every branch's frame, then read one response per fd; "
     "items are (fd, tail, payload, att, cid, lead) tuples"},
    {nullptr, nullptr, 0, nullptr},
};

static PyModuleDef native_module = {
    PyModuleDef_HEAD_INIT, "_native",
    "native IO engine for brpc_tpu (epoll + tpu_std framing in C++)", -1,
    module_methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) {
  NativeBufType.tp_name = "brpc_tpu.native.NativeBuf";
  NativeBufType.tp_basicsize = sizeof(NativeBuf);
  NativeBufType.tp_dealloc = (destructor)NativeBuf_dealloc;
  NativeBufType.tp_flags = Py_TPFLAGS_DEFAULT;
  NativeBufType.tp_as_buffer = &NativeBuf_as_buffer;
  NativeBufType.tp_as_sequence = &NativeBuf_as_sequence;
  NativeBufType.tp_doc = "malloc-backed buffer owned by the native engine";
  if (PyType_Ready(&NativeBufType) < 0) return nullptr;

  EngineType.tp_name = "brpc_tpu.native.Engine";
  EngineType.tp_basicsize = sizeof(EngineObj);
  EngineType.tp_dealloc = (destructor)Engine_dealloc;
  EngineType.tp_flags = Py_TPFLAGS_DEFAULT;
  EngineType.tp_methods = Engine_methods;
  EngineType.tp_new = Engine_new;
  EngineType.tp_doc = "epoll IO engine: C++ read/frame/write, Python dispatch";
  if (PyType_Ready(&EngineType) < 0) return nullptr;

  DemuxType.tp_name = "brpc_tpu.native.ClientDemux";
  DemuxType.tp_basicsize = sizeof(DemuxObj);
  DemuxType.tp_dealloc = (destructor)Demux_dealloc;
  DemuxType.tp_flags = Py_TPFLAGS_DEFAULT;
  DemuxType.tp_methods = Demux_methods;
  DemuxType.tp_new = Demux_new;
  DemuxType.tp_doc =
      "native client completion lane: epoll demux of response frames, "
      "cid-correlated against an in-flight table, batched completion "
      "delivery (one GIL entry per read burst)";
  if (PyType_Ready(&DemuxType) < 0) return nullptr;

  PyObject* m = PyModule_Create(&native_module);
  if (!m) return nullptr;
  Py_INCREF(&EngineType);
  PyModule_AddObject(m, "Engine", (PyObject*)&EngineType);
  Py_INCREF(&NativeBufType);
  PyModule_AddObject(m, "NativeBuf", (PyObject*)&NativeBufType);
  Py_INCREF(&DemuxType);
  PyModule_AddObject(m, "ClientDemux", (PyObject*)&DemuxType);
  // client-lane fallback reason codes (closed enum; Python mirrors)
  PyModule_AddIntConstant(m, "CFB_UNKNOWN_CID", CFB_UNKNOWN_CID);
  PyModule_AddIntConstant(m, "CFB_META_UNPARSED", CFB_META_UNPARSED);
  PyModule_AddIntConstant(m, "CFB_META_TAGS", CFB_META_TAGS);
  PyModule_AddIntConstant(m, "CFB_STREAM_FRAME", CFB_STREAM_FRAME);
  PyModule_AddIntConstant(m, "CFB_UNKNOWN_MAGIC", CFB_UNKNOWN_MAGIC);
  PyModule_AddIntConstant(m, "EV_OPEN", EV_OPEN);
  PyModule_AddIntConstant(m, "EV_MESSAGE", EV_MESSAGE);
  PyModule_AddIntConstant(m, "EV_ACK", EV_ACK);
  PyModule_AddIntConstant(m, "EV_UNKNOWN", EV_UNKNOWN);
  PyModule_AddIntConstant(m, "EV_CLOSE", EV_CLOSE);
  PyModule_AddIntConstant(m, "EV_STREAM", EV_STREAM);
  PyModule_AddIntConstant(m, "EV_HTTP", EV_HTTP);
  PyModule_AddIntConstant(m, "EV_BYTES", EV_BYTES);
  return m;
}
