"""Native C++ IO engine — build-on-demand loader.

The engine (src/engine.cpp) runs epoll loops, tpu_std frame cutting and
vectored writes in C++ with the GIL released; Python is entered once per
complete message.  This is the framework's native-performance data plane
(SURVEY.md §2's "C++, not Python stand-ins" requirement); the pure-Python
transport remains the fallback and the full multi-protocol path.

``load()`` compiles ``_native.so`` with g++ on first use (cached by
mtime) and returns the module, or None when no toolchain is available —
callers must treat None as "use the Python transport".
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

from ..butil.logging_util import LOG

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_module = None
_tried = False


def load() -> Optional[object]:
    """The compiled engine module, building it if needed (None if the
    build fails — callers fall back to the Python transport).

    With ``BRPC_TPU_NATIVE_ASAN=1`` in the environment the sanitizer-
    hardened build (``make asan`` → ``_native_asan.so``) is loaded
    instead — the host python must have libasan LD_PRELOADed (the
    sanitizer stress test's subprocess arranges this; see
    tests/asan_driver.py)."""
    global _module, _tried
    with _lock:
        if _module is not None or _tried:
            return _module
        _tried = True
        asan = os.environ.get("BRPC_TPU_NATIVE_ASAN") == "1"
        so = os.path.join(_DIR,
                          "_native_asan.so" if asan else "_native.so")
        src = os.path.join(_DIR, "src", "engine.cpp")
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                LOG.info("building native engine (%s)...",
                         os.path.basename(so))
                target = ["asan"] if asan else []
                subprocess.run(["make", "-C", _DIR] + target, check=True,
                               capture_output=True, timeout=240)
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "brpc_tpu.native._native", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _module = mod
        except Exception as e:
            LOG.warning("native engine unavailable (%s); "
                        "using the Python transport", e)
            _module = None
        return _module


def available() -> bool:
    return load() is not None
