"""Profilers behind the /hotspots portal.

Role parity with the reference's hotspots_service
(/root/reference/src/brpc/builtin/hotspots_service.cpp:35-40,483-486 —
CPU / heap / growth / contention via pprof+tcmalloc), re-designed for
this runtime:

- CPU: a sampling profiler over ``sys._current_frames()`` (the server's
  Python work — dispatch glue, user handlers, client libraries).  The
  native engine's C loops never show up here by design: their cost is
  visible as the *absence* of Python samples (and through engine.stats).
- Contention: butex waits and fiber blocking sections record wait sites
  while a collection window is active (zero overhead otherwise).
- Heap/growth: tracemalloc window diffs.
- Device: ``jax.profiler`` trace capture, served as a tarball that loads
  in Perfetto/TensorBoard (the TPU half of the story — XLA owns the
  device timeline, we own capture+serving).

Outputs: flat top tables, folded stacks (flamegraph.pl format), and a
self-contained HTML flame graph.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# CPU sampling profiler
# --------------------------------------------------------------------------


class CpuProfile:
    def __init__(self, folded: Dict[Tuple[str, ...], int], seconds: float,
                 hz: int, samples: int):
        self.folded = folded
        self.seconds = seconds
        self.hz = hz
        self.samples = samples


def sample_cpu(seconds: float = 5.0, hz: int = 99,
               skip_thread: Optional[int] = None) -> CpuProfile:
    """Sample all Python thread stacks for ``seconds`` at ``hz``.
    ``skip_thread`` excludes the calling (profiling) thread itself."""
    folded: Dict[Tuple[str, ...], int] = defaultdict(int)
    period = 1.0 / max(1, hz)
    end = time.monotonic() + seconds
    n = 0
    me = threading.get_ident()
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me or tid == skip_thread:
                continue
            stack: List[str] = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                stack.append(f"{os.path.basename(code.co_filename)}:"
                             f"{code.co_name}")
                f = f.f_back
                depth += 1
            if stack:
                folded[tuple(reversed(stack))] += 1
        n += 1
        time.sleep(period)
    return CpuProfile(dict(folded), seconds, hz, n)


def render_folded(folded: Dict[Tuple[str, ...], int]) -> str:
    return "".join(f"{';'.join(k)} {v}\n"
                   for k, v in sorted(folded.items()))


def render_flat(folded: Dict[Tuple[str, ...], int], top: int = 40) -> str:
    self_counts: Dict[str, int] = defaultdict(int)
    total_counts: Dict[str, int] = defaultdict(int)
    total = 0
    for stack, cnt in folded.items():
        total += cnt
        self_counts[stack[-1]] += cnt
        for fn in set(stack):
            total_counts[fn] += cnt
    lines = [f"{'self%':>7} {'total%':>7}  function", "-" * 60]
    for fn, cnt in sorted(self_counts.items(), key=lambda kv: -kv[1])[:top]:
        lines.append(f"{100*cnt/max(1,total):7.2f} "
                     f"{100*total_counts[fn]/max(1,total):7.2f}  {fn}")
    return "\n".join(lines) + "\n"


def render_flame_html(folded: Dict[Tuple[str, ...], int],
                      title: str = "cpu profile") -> str:
    """Self-contained HTML flame graph (no external assets)."""
    # build the tree
    root: dict = {"name": "all", "value": 0, "children": {}}
    for stack, cnt in folded.items():
        root["value"] += cnt
        node = root
        for fn in stack:
            child = node["children"].get(fn)
            if child is None:
                child = node["children"][fn] = \
                    {"name": fn, "value": 0, "children": {}}
            child["value"] += cnt
            node = child
    rows: List[str] = []
    total = max(1, root["value"])

    import html as _html

    def emit(node, depth, left):
        width = 100.0 * node["value"] / total
        if width < 0.1:
            return
        pct = 100.0 * node["value"] / total
        color = f"hsl({(hash(node['name']) % 60) + 10},70%,60%)"
        name = _html.escape(node["name"])
        label = name if width > 3 else ""
        rows.append(
            f'<div class="f" title="{name} '
            f'({node["value"]} samples, {pct:.1f}%)" '
            f'style="left:{left:.3f}%;width:{width:.3f}%;'
            f'top:{depth * 18}px;background:{color}">{label}</div>')
        child_left = left
        for child in sorted(node["children"].values(),
                            key=lambda c: -c["value"]):
            emit(child, depth + 1, child_left)
            child_left += 100.0 * child["value"] / total

    emit(root, 0, 0.0)
    height = 18 * (1 + max((len(s) for s in folded), default=1))
    return f"""<!doctype html><html><head><meta charset="utf-8">
<title>{title}</title><style>
body{{font:12px monospace;margin:8px}}
.wrap{{position:relative;height:{height}px;border:1px solid #ccc}}
.f{{position:absolute;height:16px;overflow:hidden;white-space:nowrap;
   border-radius:2px;border:1px solid rgba(0,0,0,.15);cursor:default;
   font-size:10px;padding:0 2px;box-sizing:border-box}}
</style></head><body>
<h3>{title}</h3>
<p>hover for samples; <a href="?view=folded">folded</a> |
<a href="?view=flat">flat</a></p>
<div class="wrap">{''.join(rows)}</div>
</body></html>"""


# --------------------------------------------------------------------------
# Contention profiler (butex / fiber blocking wait sites)
# --------------------------------------------------------------------------

_contention_lock = threading.Lock()
_contention_active = False
_contention_sites: Dict[Tuple[str, Tuple[str, ...]], List[float]] = {}
_contention_window = threading.Lock()    # one window at a time
_growth_window = threading.Lock()


def contention_active() -> bool:
    return _contention_active


def timed_wait(kind: str, fn):
    """Run a blocking wait ``fn`` and record its duration against the
    caller's stack when a contention window is open."""
    t0 = time.monotonic()
    ok = fn()
    record_wait(kind, time.monotonic() - t0, skip_frames=2)
    return ok


def record_wait(kind: str, waited_s: float, skip_frames: int = 2) -> None:
    """Called by blocking primitives when a window is active."""
    if not _contention_active or waited_s <= 0:
        return
    f = sys._getframe(skip_frames)
    stack: List[str] = []
    depth = 0
    while f is not None and depth < 24:
        code = f.f_code
        stack.append(f"{os.path.basename(code.co_filename)}:"
                     f"{code.co_name}")
        f = f.f_back
        depth += 1
    key = (kind, tuple(reversed(stack)))
    with _contention_lock:
        _contention_sites.setdefault(key, []).append(waited_s)


def collect_contention(seconds: float = 5.0) -> str:
    """Open a collection window, then report wait sites ranked by total
    waited time (≈ contention profiler semantics).  One window at a
    time: concurrent requests would wipe each other's data."""
    global _contention_active
    if not _contention_window.acquire(blocking=False):
        return "another contention window is active; retry later\n"
    try:
        with _contention_lock:
            _contention_sites.clear()
        _contention_active = True
        try:
            time.sleep(seconds)
        finally:
            _contention_active = False
    finally:
        _contention_window.release()
    with _contention_lock:
        items = [(kind, stack, len(w), sum(w))
                 for (kind, stack), w in _contention_sites.items()]
    items.sort(key=lambda it: -it[3])
    lines = [f"contention over {seconds:.1f}s window",
             f"{'total_ms':>9} {'waits':>6}  kind  wait site", "-" * 72]
    for kind, stack, n, total in items[:50]:
        site = ";".join(stack[-4:])
        lines.append(f"{total*1e3:9.1f} {n:6d}  {kind:<5} {site}")
    if not items:
        lines.append("(no recorded waits — uncontended or idle)")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# Heap / growth (tracemalloc windows)
# --------------------------------------------------------------------------

def collect_growth(seconds: float = 5.0, top: int = 30) -> str:
    import tracemalloc
    if not _growth_window.acquire(blocking=False):
        return "another growth window is active; retry later\n"
    try:
        started_here = not tracemalloc.is_tracing()
        if started_here:
            tracemalloc.start()
        try:
            before = tracemalloc.take_snapshot()
            time.sleep(seconds)
            after = tracemalloc.take_snapshot()
        finally:
            if started_here:
                tracemalloc.stop()
    finally:
        _growth_window.release()
    stats = after.compare_to(before, "lineno")
    lines = [f"heap growth over {seconds:.1f}s window",
             f"{'delta_kb':>9} {'count':>7}  allocation site", "-" * 72]
    for s in stats[:top]:
        if s.size_diff == 0:
            continue
        frame = s.traceback[0]
        lines.append(f"{s.size_diff/1024:9.1f} {s.count_diff:7d}  "
                     f"{os.path.basename(frame.filename)}:{frame.lineno}")
    return "\n".join(lines) + "\n"


def collect_heap(top: int = 30) -> str:
    import tracemalloc
    if not tracemalloc.is_tracing():
        return ("tracemalloc is not tracing; GET /hotspots/growth first "
                "(or start the process with PYTHONTRACEMALLOC=1) for live "
                "heap attribution\n")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    lines = [f"{'kb':>9} {'count':>7}  allocation site", "-" * 72]
    for s in stats[:top]:
        frame = s.traceback[0]
        lines.append(f"{s.size/1024:9.1f} {s.count:7d}  "
                     f"{os.path.basename(frame.filename)}:{frame.lineno}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# Device (jax.profiler) capture
# --------------------------------------------------------------------------

def collect_device_trace(seconds: float = 3.0) -> Tuple[bytes, str]:
    """Capture a jax.profiler trace window; returns (tar.gz bytes,
    filename).  Loads in Perfetto / TensorBoard."""
    import io
    import shutil
    import tarfile
    import tempfile

    import jax

    tmp = tempfile.mkdtemp(prefix="hotspots_device_")
    try:
        jax.profiler.start_trace(tmp)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        bio = io.BytesIO()
        with tarfile.open(fileobj=bio, mode="w:gz") as tar:
            tar.add(tmp, arcname="device_trace")
        name = f"device_trace_{int(time.time())}.tar.gz"
        return bio.getvalue(), name
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
