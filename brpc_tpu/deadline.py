"""Deadline plane — the cross-cutting end-to-end deadline state.

The wire already carried a remaining-deadline everywhere (tpu_std meta
TLV 13, ``grpc-timeout`` on h2, and now ``x-deadline-ms`` on HTTP/1.1);
this module is the shared machinery that makes it MEAN something:

- **doomed-work shedding** (server side): every dispatch path checks,
  right before user code would run, whether the request's propagated
  deadline already expired while the frame sat in native batches,
  fiber queues or pipelined bursts — and answers ``ERPCTIMEDOUT``
  without burning handler time ("RPC Considered Harmful": tail-latency
  amplification comes from servers working on requests whose caller
  has given up).  Sheds are reason-coded per ``(lane, method)`` and
  exported as the ``deadline_shed_total`` bvar family (and on the
  ``/native`` portal page).  ≈ brpc ``-server_fail_fast``.
- **ambient inheritance** (client side inside a handler): dispatch
  wraps user code in :class:`inherit_deadline`, so any downstream RPC
  issued from the handler's call stack defaults its own timeout to the
  inherited remaining budget minus elapsed — and fails fast at ≤0
  instead of dispatching work the upstream caller will never see.
  The ambient mark is a plain thread-local: it covers the handler's
  synchronous call stack (inline native shims and fiber-pool handlers
  alike); work a handler hands to OTHER threads (``begin_async``
  completions) must propagate ``cntl.deadline_remaining_ms()`` itself.

Shedding is live-togglable via the ``enable_deadline_shed`` flag —
the bench's ``goodput_under_overload`` A/B flips it to price exactly
what doomed work costs a saturated server.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .butil.flags import define_flag, get_flag
from .butil.time_utils import monotonic_us

define_flag("enable_deadline_shed", True,
            "answer ERPCTIMEDOUT for requests whose propagated deadline "
            "expired in queue, without invoking the handler",
            validator=lambda v: isinstance(v, bool))


def shed_enabled() -> bool:
    return bool(get_flag("enable_deadline_shed", True))


# ---------------------------------------------------------------------------
# shed accounting: plain dict under a lock (read-modify-write on a dict
# slot is not atomic; sheds come from engine loops AND fiber threads).
# Exposed eagerly as the deadline_shed_total{lane,method} bvar family so
# a scrape keyed on it never depends on a shed having happened.
# ---------------------------------------------------------------------------

_shed_lock = threading.Lock()
_shed: Dict[Tuple[str, str], int] = {}

from .bvar.multi_dimension import PassiveDimension as _PassiveDimension

_shed_var = _PassiveDimension(
    ("lane", "method"), lambda: shed_counters(),
    name="deadline_shed_total")


def record_shed(lane: str, method: str) -> None:
    with _shed_lock:
        _shed[(lane, method)] = _shed.get((lane, method), 0) + 1


def shed_counters() -> Dict[Tuple[str, str], int]:
    """Snapshot of the per-(lane, method) shed counters."""
    with _shed_lock:
        return dict(_shed)


def maybe_shed(cntl, lane: str, method: str) -> bool:
    """The one shedding decision, shared by all five server paths.

    True ⇢ the request's propagated deadline expired before user code
    could run: the shed is recorded, the span (when sampled) annotated,
    and ``cntl`` failed with ``ERPCTIMEDOUT`` — the CALLER completes it
    (``cntl.finish(None)``) so each path's own error serializer answers
    the client (error frame, HTTP 500 + x-rpc-error-code, grpc-status 4).
    """
    d = getattr(cntl, "deadline_us", 0)
    if not d:
        return False
    late_ms = (monotonic_us() - d) / 1000.0
    if late_ms < 0 or not shed_enabled():
        return False
    record_shed(lane, method)
    span = getattr(cntl, "span", None)
    if span is not None:
        span.annotate(f"deadline expired {late_ms:.1f}ms before dispatch;"
                      f" shed on the {lane} lane")
    from .butil.status import Errno
    cntl.set_failed(int(Errno.ERPCTIMEDOUT),
                    f"deadline expired {late_ms:.1f}ms before dispatch "
                    "(doomed work shed)")
    return True


def arm(cntl, timeout_ms: Optional[int],
        arrival_us: Optional[int] = None) -> None:
    """Anchor ``cntl``'s absolute deadline at the request's ARRIVAL —
    the protocol parse timestamp when the path has one (the engine's
    CLOCK_MONOTONIC parse stamp on the native lanes, the message-cut
    stamp elsewhere), else the controller's construction time.
    ``timeout_ms == 0`` means expired-at-arrival (an ``x-deadline-ms:
    0`` header); None means no deadline."""
    if timeout_ms is None or timeout_ms < 0:
        return
    base = arrival_us if arrival_us else cntl.begin_time_us
    cntl.deadline_us = base + int(timeout_ms) * 1000


def parse_deadline_ms(value) -> Optional[int]:
    """The one ``x-deadline-ms`` header parse, shared by the classic and
    slim HTTP lanes so they can never disagree on whether the same
    request carries a deadline.  Accepts str or bytes; returns the
    remaining budget in ms (0 = already expired) or None when absent or
    malformed."""
    if value is None:
        return None
    if isinstance(value, (bytes, memoryview)):
        value = bytes(value).decode("latin1")
    value = value.strip()
    return int(value) if value.isdigit() else None


# ---------------------------------------------------------------------------
# ambient inheritance
# ---------------------------------------------------------------------------

_tls = threading.local()


def ambient_deadline_us() -> int:
    """The enclosing server request's absolute deadline (monotonic µs),
    or 0 when the current call stack is not under a deadline'd handler."""
    return getattr(_tls, "deadline_us", 0)


def ambient_remaining_ms() -> Optional[float]:
    """Remaining budget of the enclosing server request (may be ≤ 0:
    callers fail fast), or None outside a deadline'd handler."""
    d = ambient_deadline_us()
    if not d:
        return None
    return (d - monotonic_us()) / 1000.0


def cap_timeout_ms(timeout_ms: Optional[int]) -> Tuple[Optional[int], bool]:
    """Apply ambient inheritance to a client call's timeout: returns
    ``(effective_timeout_ms, expired)``.  Outside a deadline'd handler
    the timeout passes through.  Inside one, the call can never outlive
    the upstream budget — an unset/infinite timeout becomes the
    remaining budget, a longer one is clamped to it, and ``expired``
    is True when the budget is already gone (callers fail fast with
    ``ERPCTIMEDOUT`` instead of dispatching doomed work)."""
    amb = ambient_remaining_ms()
    if amb is None:
        return timeout_ms, False
    if amb <= 0:
        return 0, True
    cap = max(1, int(amb))
    if timeout_ms is None or timeout_ms <= 0 or timeout_ms > cap:
        return cap, False
    return timeout_ms, False


# ---------------------------------------------------------------------------
# retry hardening
# ---------------------------------------------------------------------------

class RetryBudget:
    """gRPC-style retry-throttling token bucket (the A6 retry design,
    same shape as brpc's RetryPolicy + CircuitBreaker pairing): a
    channel starts with ``max_tokens``; every retry or backup attempt
    COSTS one token and is denied when fewer than half the tokens
    remain; every successful response REFILLS ``token_ratio``.  Under a
    degraded backend the sustained retry rate is therefore bounded at
    ``token_ratio`` retries per successful call — a retry storm decays
    to ~1+ratio amplification instead of multiplying offered load by
    1+max_retry."""

    __slots__ = ("max_tokens", "token_ratio", "_tokens", "_lock",
                 "denied_count")

    def __init__(self, max_tokens: float = 10.0,
                 token_ratio: float = 0.1):
        self.max_tokens = float(max_tokens)
        self.token_ratio = float(token_ratio)
        self._tokens = float(max_tokens)
        self._lock = threading.Lock()
        self.denied_count = 0

    def acquire(self) -> bool:
        """Spend one token for a retry/backup attempt; False = the
        budget is exhausted and the attempt must NOT be sent."""
        with self._lock:
            if self._tokens > self.max_tokens / 2.0:
                self._tokens -= 1.0
                return True
            self.denied_count += 1
            return False

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens,
                               self._tokens + self.token_ratio)

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


def backoff_ms(base_ms: int, nretry: int, max_ms: int = 5000,
               jitter: float = 0.2) -> float:
    """Exponential backoff with multiplicative jitter for retry attempt
    ``nretry`` (1-based): ``base * 2^(n-1)`` scaled by a uniform
    ±``jitter`` factor so synchronized clients don't re-storm in phase,
    then capped at ``max_ms`` (the cap is a hard bound operators size
    timeouts around — jitter never pierces it).  base_ms <= 0 disables
    (returns 0)."""
    if base_ms <= 0 or nretry <= 0:
        return 0.0
    d = float(base_ms * (1 << min(nretry - 1, 20)))
    if jitter > 0:
        from .butil.fast_rand import fast_rand
        u = (fast_rand() % 10_000) / 10_000.0       # [0, 1)
        d *= 1.0 - jitter + 2.0 * jitter * u        # [1-j, 1+j)
    return min(float(max_ms), d)


class inherit_deadline:
    """Context manager the dispatch paths wrap user code in: while the
    handler runs, its controller's deadline is the thread's ambient
    budget, consumed by every client launch path (``Controller._launch``,
    the fast lanes, gRPC, ParallelChannel).  No-op (and no TLS write)
    when the request carries no deadline."""

    __slots__ = ("_d", "_prev")

    def __init__(self, cntl):
        self._d = getattr(cntl, "deadline_us", 0) or 0
        self._prev = 0

    def __enter__(self):
        if self._d:
            self._prev = getattr(_tls, "deadline_us", 0)
            _tls.deadline_us = self._d
        return self

    def __exit__(self, *exc):
        if self._d:
            _tls.deadline_us = self._prev
        return False
