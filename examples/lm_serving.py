"""LM serving — completions over the framework.

Starts an LMService (TransformerLM + KV-cache greedy decode), then a
client requests completions over plain RPC.  The first request pays the
XLA compile; the rest reuse the cached prefill/decode programs.

Run: python examples/lm_serving.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np

    from brpc_tpu.client import Channel, Controller
    from brpc_tpu.models.lm_service import (LMService,
                                            pack_generate_request,
                                            unpack_generated)
    from brpc_tpu.server import Server

    srv = Server()
    srv.add_service(LMService(), name="LM")
    assert srv.start("127.0.0.1:0") == 0
    ch = Channel()
    ch.init(str(srv.listen_endpoint))

    info = ch.call("LM.Info", b"")
    print("model:", info.decode())

    prompt = np.arange(12, dtype=np.int32).reshape(1, 12)
    for i in range(3):
        cntl = Controller()
        cntl.timeout_ms = 120_000
        t0 = time.perf_counter()
        c = ch.call_method("LM.Generate",
                           pack_generate_request(prompt, 16), cntl=cntl)
        dt = time.perf_counter() - t0
        assert not c.failed, c.error_text
        ids = unpack_generated(c.response)
        label = "compiles" if i == 0 else "cached"
        print(f"request {i} ({label}): {dt*1e3:7.1f} ms  "
              f"-> {ids[0][:8].tolist()}...")
    srv.stop()


if __name__ == "__main__":
    main()
