"""ParallelChannel fan-out (≈ reference example/parallel_echo_c++):
one call fans to 3 servers, responses merge; one dead sub-channel is
tolerated with fail_limit.  Run: python examples/parallel_echo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from brpc_tpu.client import Channel, ChannelOptions          # noqa: E402
from brpc_tpu.client.parallel_channel import ParallelChannel  # noqa: E402
from brpc_tpu.server import Server, Service                   # noqa: E402


class Shard(Service):
    def __init__(self, label: bytes):
        self.label = label

    def Get(self, cntl, request):
        return self.label + b":" + request


def main():
    servers = []
    for i in range(3):
        s = Server()
        s.add_service(Shard(b"shard%d" % i), name="Shard")
        assert s.start("127.0.0.1:0") == 0
        servers.append(s)

    pc = ParallelChannel(fail_limit=1)
    for s in servers:
        sub = Channel(ChannelOptions())
        sub.init(str(s.listen_endpoint))
        pc.add_channel(sub)

    c = pc.call_method("Shard.Get", b"key42")
    assert not c.failed, c.error_text
    print("merged response:", c.response)

    for s in servers:
        s.stop()


if __name__ == "__main__":
    main()
