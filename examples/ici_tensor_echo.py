"""Device-resident tensor echo — this framework's rdma_performance
analogue (≈ reference example/rdma_performance): a JAX array rides an
RPC as a DEVICE attachment (descriptor on the wire, payload through the
device fabric with window/ack flow control; zero host copies when the
fabric is reachable).  Run: python examples/ici_tensor_echo.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                                    # noqa: E402
import jax.numpy as jnp                                       # noqa: E402

from brpc_tpu.client import Channel, Controller               # noqa: E402
from brpc_tpu.models.ps_service import PSService              # noqa: E402
from brpc_tpu.server import Server                            # noqa: E402


def main():
    server = Server()
    server.add_service(PSService(), name="PS")
    assert server.start("127.0.0.1:0") == 0

    channel = Channel()
    channel.init(str(server.listen_endpoint))

    x = jnp.arange((1 << 20) // 4, dtype=jnp.float32)      # 1MB in HBM
    x.block_until_ready()
    print(f"backend={jax.default_backend()} tensor={x.nbytes} bytes")

    # warm (first exchange handshakes the fabric domain)
    for _ in range(3):
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = channel.call_method("PS.EchoTensor", b"", cntl=cntl)
        assert not c.failed, c.error_text
        out = c.response_device_attachment.tensor()

    n = 100
    t0 = time.perf_counter()
    for _ in range(n):
        cntl = Controller()
        cntl.timeout_ms = 30_000
        cntl.request_device_attachment = x
        c = channel.call_method("PS.EchoTensor", b"", cntl=cntl)
        out = c.response_device_attachment.tensor()
    dt = time.perf_counter() - t0
    assert out is x, "device path should be zero-copy end to end"
    print(f"{n} echoes of {x.nbytes} bytes: "
          f"{n * x.nbytes * 2 / dt / 1e9:.2f} GB/s device-resident")
    server.stop()


if __name__ == "__main__":
    main()
