"""Checkpoint/resume — training state surviving preemption.

Trains the TransformerLM, checkpoints every few steps, then simulates a
preemption: a fresh process-state resumes from the newest step with
shardings restored in place and continues bit-identically.

Run: python examples/checkpoint_resume.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from brpc_tpu.models import LMConfig, init_params, make_train_step
    from brpc_tpu.utils import TrainCheckpointer, abstract_like

    cfg = LMConfig(vocab=128, dim=64, heads=4, depth=2, lr=0.2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.tile(jnp.arange(32, dtype=jnp.int32), (4, 2))
    labels = jnp.roll(ids, -1, axis=-1)
    step = jax.jit(make_train_step(cfg))

    workdir = tempfile.mkdtemp(prefix="ckpt_demo_")
    ckpt = TrainCheckpointer(workdir, max_to_keep=2)
    print(f"checkpoints -> {workdir}")

    state = {"params": params, "step": jnp.int32(0)}
    for i in range(1, 9):
        p, loss = step(state["params"], ids, labels)
        state = {"params": p, "step": jnp.int32(i)}
        if i % 2 == 0:
            ckpt.save(i, state)
        print(f"step {i}  loss {float(loss):.4f}")
    final_before = state

    print(f"\n-- simulated preemption; kept steps: {ckpt.all_steps()} --\n")

    # resume from the OLDER kept step so the replayed tail is real work
    # (shards land straight on their devices via the abstract target)
    oldest = min(ckpt.all_steps())
    restored = ckpt.restore(step=oldest, like=abstract_like(final_before))
    start = int(restored["step"]) + 1
    state = restored
    for i in range(start, 9):
        p, loss = step(state["params"], ids, labels)
        state = {"params": p, "step": jnp.int32(i)}
        print(f"resumed step {i}  loss {float(loss):.4f}")

    same = jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool(jnp.array_equal(a, b)),
        state["params"], final_before["params"]))
    print(f"\nresumed trajectory bit-identical to uninterrupted: {same}")
    assert same
    ckpt.close()


if __name__ == "__main__":
    main()
