"""Raw latency lane — the fastest supported path for echo-class RPCs.

A ``@raw_method`` handler receives zero-copy views into the transport
frame and returns bytes; ``Channel.call_raw`` completes the round trip
with no Controller in the path on either side (≈ the discipline of the
reference's example/echo_c++ benchmark handler,
/root/reference/docs/cn/benchmark.md:57).  Shows: raw round trips with
latency percentiles, a pipelined raw batch, and that per-method stats
survive the slim dispatch.  Run: python examples/raw_echo.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from brpc_tpu.client import Channel                          # noqa: E402
from brpc_tpu.server import Server, ServerOptions, Service   # noqa: E402
from brpc_tpu.server.service import raw_method               # noqa: E402


class EchoService(Service):
    @raw_method
    def Echo(self, payload, attachment):
        # payload/attachment are memoryviews into the received frame;
        # returning the attachment view echoes it without a copy
        return b"ok", attachment


def main():
    opts = ServerOptions()
    opts.native = True              # C++ epoll data plane
    opts.native_loops = 1
    opts.usercode_inline = True     # raw handlers never block
    server = Server(opts)
    assert server.add_service(EchoService()) == 0
    assert server.start("127.0.0.1:0") == 0
    addr = str(server.listen_endpoint)
    print(f"server at {addr}")

    ch = Channel()
    assert ch.init(addr) == 0

    att = bytes(1024)
    resp, echoed = ch.call_raw("EchoService.Echo", b"hello", att)
    assert bytes(resp) == b"ok" and bytes(echoed) == att
    print("raw echo ok: 1KB attachment round-tripped zero-copy")

    for _ in range(300):            # warm the pinned connection
        ch.call_raw("EchoService.Echo", b"", att)
    lats = []
    for _ in range(2000):
        t0 = time.perf_counter()
        ch.call_raw("EchoService.Echo", b"", att)
        lats.append((time.perf_counter() - t0) * 1e6)
    lats.sort()
    print(f"2000 raw 1KB echos: p50 {lats[len(lats) // 2]:.0f}us  "
          f"p99 {lats[int(len(lats) * 0.99)]:.0f}us")

    reqs = [b"x" * 64] * 256
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < 2.0:
        ch.call_batch("EchoService.Echo", reqs)
        n += len(reqs)
    print(f"pipelined raw 64B: {n / (time.perf_counter() - t0):,.0f} qps")

    entry = server.find_method("EchoService", "Echo")
    print(f"method stats survive the slim path: "
          f"{entry.status.latency.count()} calls recorded, "
          f"qps window {entry.status.latency.qps():.0f}")

    server.stop()
    print("done")


if __name__ == "__main__":
    main()
