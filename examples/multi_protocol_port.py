"""One native listener, every protocol.

The C++ engine cuts tpu_std frames and HTTP/1.x natively; everything
else (gRPC-over-h2, redis RESP, thrift) rides the passthrough lane into
the protocol registry.  This example starts ONE server and talks to it
with four different clients.

Run:  python examples/multi_protocol_port.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import http.client
import json

from brpc_tpu.client import Channel
from brpc_tpu.client.redis_client import RedisClient
from brpc_tpu.server import Server, ServerOptions, Service
from brpc_tpu.server.service import raw_method


class Calc(Service):
    def Add(self, cntl, request):
        data = json.loads(request or b"{}")
        return {"sum": int(data.get("a", 0)) + int(data.get("b", 0))}

    def Echo(self, cntl, request):
        return request

    @raw_method(native="echo")
    def EchoRaw(self, payload, attachment):
        # answered inside the C++ engine — zero Python per request
        return payload, attachment


class MiniRedis:
    def __init__(self):
        self.store = {}

    def on_command(self, args):
        cmd = args[0].upper()
        if cmd == b"PING":
            return "PONG"
        if cmd == b"SET":
            self.store[args[1]] = args[2]
            return "OK"
        if cmd == b"GET":
            return self.store.get(args[1])
        from brpc_tpu.protocol.resp import RedisError
        raise RedisError(f"unknown command {cmd.decode()}")


def main() -> None:
    opts = ServerOptions()
    opts.native = True             # the C++ engine owns the listener
    opts.usercode_inline = True    # echo-class handlers never block
    srv = Server(opts)
    srv.add_service(Calc(), name="Calc")
    srv.add_service(MiniRedis(), name="redis")
    assert srv.start("127.0.0.1:0") == 0
    ep = srv.listen_endpoint
    print(f"one native listener at {ep}\n")

    # 1. tpu_std raw lane (C++-answered echo)
    ch = Channel()
    ch.init(str(ep))
    resp, _ = ch.call_raw("Calc.EchoRaw", b"tpu_std bytes")
    print("tpu_std  ->", bytes(resp))

    # 2. HTTP/1.1 (C++-cut, Python-dispatched; also serves the portal)
    hc = http.client.HTTPConnection(ep.host, ep.port, timeout=10)
    hc.request("POST", "/Calc/Add", body=json.dumps({"a": 20, "b": 22}),
               headers={"Content-Type": "application/json"})
    print("http     ->", hc.getresponse().read().decode().strip())
    hc.close()

    # 3. gRPC over h2 (passthrough lane), with a real grpcio client
    try:
        import grpc
        ident = lambda b: b  # noqa: E731
        with grpc.insecure_channel(f"{ep.host}:{ep.port}") as gch:
            fn = gch.unary_unary("/Calc/Echo", request_serializer=ident,
                                 response_deserializer=ident)
            print("grpc     ->", fn(b"unary over h2", timeout=10))
    except ImportError:
        print("grpc     -> (grpcio not installed, skipped)")

    # 4. redis RESP (passthrough lane)
    r = RedisClient(str(ep))
    r.set("greeting", b"hello from RESP")
    print("redis    ->", r.get("greeting"))
    r.close()

    srv.stop()


if __name__ == "__main__":
    main()
