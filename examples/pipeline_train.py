"""GPipe pipeline-parallel training in one differentiated program.

``make_pipeline_train`` writes the microbatch conveyor as a
``lax.scan`` inside ``shard_map``; reverse-mode AD through it IS the
backward conveyor (ppermute transposes to the inverted ring) with
microbatch gradient accumulation.  Loss and stage-sharded grads match
the unpipelined model exactly.

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/pipeline_train.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon sitecustomize pins the platform to the 1-chip TPU;
        # honor the caller's explicit request for virtual CPU devices
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from brpc_tpu.parallel.pipeline import make_pipeline_train

    n = jax.device_count()
    print(f"{n} devices on {jax.default_backend()}")
    mesh = Mesh(np.array(jax.devices()), ("pp",))
    width, n_micro, mb = 32, 8, 4

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def loss_fn(outputs, ys):
        return jnp.mean((outputs - ys) ** 2)

    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    params = {
        "w": jax.device_put(
            jax.random.normal(ks[0], (n, width, width)) * 0.3,
            NamedSharding(mesh, P("pp"))),
        "b": jax.device_put(
            jax.random.normal(ks[1], (n, width)) * 0.1,
            NamedSharding(mesh, P("pp"))),
    }
    xs = jax.random.normal(ks[2], (n_micro, mb, width))
    ys = jax.random.normal(ks[3], (n_micro, mb, width))

    step = make_pipeline_train(mesh, stage_fn, loss_fn, "pp")
    lr = 0.05
    for i in range(10):
        loss, grads = step(params, xs, ys)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                        params, grads)
        print(f"step {i}: loss {float(loss):.5f}  "
              f"(grads spread over "
              f"{len(grads['w'].sharding.device_set)} devices)")


if __name__ == "__main__":
    main()
