"""Streaming RPC (≈ reference example/streaming_echo_c++): establish a
stream on an RPC, push chunks with credit-based flow control, observe
them on the server.  Run: python examples/streaming_echo.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from brpc_tpu.client import Channel, Controller              # noqa: E402
from brpc_tpu.server import Server, Service                  # noqa: E402
from brpc_tpu.streaming import (StreamOptions, stream_accept,  # noqa: E402
                                stream_create)


class StreamSink(Service):
    def __init__(self):
        self.total = 0
        self.done = threading.Event()

    def Start(self, cntl, request):
        def on_received(stream, msgs):
            self.total += sum(len(m) for m in msgs)

        def on_closed(stream):
            self.done.set()

        stream_accept(cntl, StreamOptions(on_received=on_received,
                                          on_closed=on_closed))
        return b"stream accepted"


def main():
    svc = StreamSink()
    server = Server()
    server.add_service(svc, name="Sink")
    assert server.start("127.0.0.1:0") == 0

    channel = Channel()
    channel.init(str(server.listen_endpoint))
    cntl = Controller()
    cntl.timeout_ms = 5000
    stream = stream_create(cntl, StreamOptions(max_buf_size=1 << 20))
    c = channel.call_method("Sink.Start", b"", cntl=cntl)
    assert not c.failed, c.error_text
    print("server said:", c.response)

    chunk = b"x" * 65536
    for _ in range(64):                  # 4MB through the stream
        assert stream.write(chunk) == 0
    stream.close()
    svc.done.wait(10)
    print(f"server received {svc.total} bytes over the stream")
    server.stop()


if __name__ == "__main__":
    main()
