"""TransformerLM — train the long-context flagship on a device mesh.

Demonstrates the dense-compute model family end to end:

- dp×tp sharded SGD training (tensor-parallel projections, data-parallel
  batch; XLA inserts the collectives from the NamedSharding specs),
- sequence-parallel ring attention for long context (the same forward
  spread over an ``sp`` axis so context length scales with chips),
- remat on, bf16 matmuls on the MXU.

Run on the virtual CPU mesh (or real chips, if you have them):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_transformer_lm.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from brpc_tpu.models import (LMConfig, batch_specs, init_params,
                                 make_forward, make_train_step, param_specs)

    n = len(jax.devices())
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
                ("dp", "tp"))
    print(f"mesh: dp={dp} tp={tp} on {jax.default_backend()}")

    cfg = LMConfig(vocab=256, dim=64, heads=4, depth=2,
                   max_seq=max(128, 16 * n), lr=0.3)
    params = init_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, param_specs(cfg))

    # toy task: predict the next token of a repeating pattern
    ids = jnp.tile(jnp.arange(64, dtype=jnp.int32), (4 * dp, 2))
    labels = jnp.roll(ids, -1, axis=-1)
    ids_spec, lbl_spec = batch_specs()
    ids = jax.device_put(ids, NamedSharding(mesh, ids_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, lbl_spec))

    step = jax.jit(make_train_step(cfg))
    with mesh:
        for i in range(20):
            params, loss = step(params, ids, labels)
            if i % 5 == 0 or i == 19:
                print(f"step {i:3d}  loss {float(loss):.4f}")

    # long context via sequence parallelism: same params, attention over
    # an sp axis — each chip holds 1/n of the sequence
    if n >= 2:
        sp_mesh = Mesh(np.array(jax.devices()), ("sp",))
        fwd = make_forward(cfg, mesh=sp_mesh, sp_axis="sp")
        long_ids = jnp.tile(jnp.arange(64, dtype=jnp.int32),
                            (2, (16 * n) // 64 + 1))[:, :16 * n]
        long_ids = jax.device_put(
            long_ids, NamedSharding(sp_mesh, P(None, "sp")))
        logits = fwd(params, long_ids)
        print(f"sequence-parallel forward over {n} chips: "
              f"logits {tuple(logits.shape)} finite="
              f"{bool(jnp.isfinite(logits).all())}")


if __name__ == "__main__":
    main()
