"""Load + observability tour: drive a server with rpc_press while
reading live stats, rpcz spans and a CPU flame profile from the builtin
portal.  Run: python examples/press_and_portal.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from brpc_tpu.server import Server, Service                   # noqa: E402
from brpc_tpu.tools.rpc_press import Press, PressOptions      # noqa: E402
from brpc_tpu.tools.rpc_view import fetch                     # noqa: E402


class Work(Service):
    def Do(self, cntl, request):
        return request[::-1]


def main():
    server = Server()
    server.add_service(Work(), name="W")
    assert server.start("127.0.0.1:0") == 0
    addr = str(server.listen_endpoint)

    popts = PressOptions()
    popts.server = addr
    popts.method = "W.Do"
    popts.qps = 500
    popts.duration_s = 3.0
    popts.input = b"payload"
    press = Press(popts)
    press.start()

    import time
    time.sleep(1.0)
    print("== /status ==")
    print(fetch(addr, "status"))
    print("== /vars (rpc related) ==")
    print(fetch(addr, "vars?filter=input_messenger"))
    print("== /hotspots/cpu (1s flame, flat view) ==")
    print(fetch(addr, "hotspots/cpu?seconds=1&view=flat"))

    press.stop()
    print("press summary:", press.summary())
    server.stop()


if __name__ == "__main__":
    main()
