"""gRPC interop (≈ reference example/grpc_c++): a real grpcio client
calls this framework's h2 server — unary and bidi streaming — then this
framework's client calls back.  Run: python examples/grpc_interop.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc                                                   # noqa: E402

from brpc_tpu.server import Server, Service, grpc_streaming   # noqa: E402

ident = lambda b: b  # noqa: E731


class EchoSvc(Service):
    def Echo(self, cntl, request):
        return request

    @grpc_streaming
    def Chat(self, cntl, msgs):
        for m in msgs:
            cntl.grpc_stream.write(m.upper())
        return None


def main():
    server = Server()
    server.add_service(EchoSvc(), name="EchoSvc")
    assert server.start("127.0.0.1:0") == 0
    ep = server.listen_endpoint

    with grpc.insecure_channel(f"{ep.host}:{ep.port}") as ch:
        unary = ch.unary_unary("/EchoSvc/Echo", request_serializer=ident,
                               response_deserializer=ident)
        print("grpcio unary:", unary(b"ping-from-grpcio", timeout=10))

        bidi = ch.stream_stream("/EchoSvc/Chat", request_serializer=ident,
                                response_deserializer=ident)
        print("grpcio bidi:", list(bidi(iter([b"alpha", b"beta"]),
                                        timeout=10)))

    # our h2 client against our own server, full circle
    from brpc_tpu.butil.endpoint import parse_endpoint
    from brpc_tpu.client.grpc_client import GrpcConnection
    conn = GrpcConnection(parse_endpoint(f"{ep.host}:{ep.port}"))
    status, msg, body = conn.unary_call("/EchoSvc/Echo", b"full-circle", 10)
    print("our h2 client:", status, body)
    call = conn.streaming_call("/EchoSvc/Chat", 10.0)
    call.write(b"stream me")
    print("our streaming client:", call.read())
    call.done_writing()
    conn.close()
    server.stop()


if __name__ == "__main__":
    main()
