"""Echo — the hello-world of the framework (≈ reference example/echo_c++).

Starts a server with the native C++ IO engine, makes sync, async and
attachment-carrying calls, then prints method stats from the builtin
portal.  Run: python examples/echo.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from brpc_tpu.butil.iobuf import IOBuf                      # noqa: E402
from brpc_tpu.client import Channel, ChannelOptions, Controller  # noqa: E402
from brpc_tpu.server import Server, ServerOptions, Service  # noqa: E402


class EchoService(Service):
    def Echo(self, cntl, request):
        # attachment rides back zero-copy, outside the payload
        cntl.response_attachment.append_iobuf(cntl.request_attachment)
        return request


def main():
    opts = ServerOptions()
    opts.native = True              # C++ epoll data plane
    opts.usercode_inline = True     # echo never blocks: run on the IO loop
    server = Server(opts)
    assert server.add_service(EchoService()) == 0
    assert server.start("127.0.0.1:0") == 0
    addr = str(server.listen_endpoint)
    print(f"server at {addr}")

    copts = ChannelOptions()
    copts.connection_type = "pooled"    # the latency fast lane
    copts.timeout_ms = 2000
    channel = Channel(copts)
    assert channel.init(addr) == 0

    # sync
    print("sync:", channel.call("EchoService.Echo", b"hello tpu-rpc"))

    # with attachment
    cntl = Controller()
    cntl.request_attachment = IOBuf(b"bulk-bytes " * 3)
    c = channel.call_method("EchoService.Echo", b"with attachment",
                            cntl=cntl)
    print("attachment back:", bytes(c.response_attachment.to_bytes()))

    # async with a done callback
    done_evt = threading.Event()

    def on_done(cntl):
        print("async:", cntl.response, f"({cntl.latency_us}us)")
        done_evt.set()

    channel.call_method("EchoService.Echo", b"fire-and-wait", done=on_done)
    done_evt.wait(5)

    # pipelined batch (the high-QPS lane)
    outs = channel.call_batch("EchoService.Echo",
                              [b"m%d" % i for i in range(8)])
    print("batch:", outs)
    server.stop()


if __name__ == "__main__":
    main()
