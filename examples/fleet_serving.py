"""Fleet-shaped serving demo: the round-4 operations stack end to end.

One script plays every role a real TPU serving fleet has:

  1. a **fleet controller** (stdlib HTTP) exporting long-poll
     membership with index resumption — the ``watch://`` naming shape;
  2. three **serving ranks** — native-engine servers whose hot method is
     answered GIL-free by the C++ engine (``@raw_method(native="echo")``)
     while rpcz spans persist to sqlite for post-mortem browsing;
  3. a **client** on a ``watch://`` channel with round-robin balancing,
     sending pipelined batches while a membership flip happens live;
  4. an **operator**: rpc_view browsing proxy over the ranks' portals +
     a parallel_http fleet probe.

Run: ``python examples/fleet_serving.py``
"""

import os
import sys
import tempfile
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import brpc_tpu.rpcz                                         # noqa: E402,F401
                       # ^ flags live with their consumers: importing
                       # rpcz DEFINES rpcz_dir so set_flag below lands
from brpc_tpu.butil.flags import set_flag                    # noqa: E402
from brpc_tpu.client import Channel                          # noqa: E402
from brpc_tpu.server import Server, ServerOptions, Service   # noqa: E402
from brpc_tpu.server.service import raw_method               # noqa: E402
from brpc_tpu.tools.parallel_http import parallel_fetch      # noqa: E402
from brpc_tpu.tools.rpc_view import ViewProxy                # noqa: E402


class Controller:
    """Blocking-query membership endpoint (the consul shape)."""

    def __init__(self):
        self.index, self.members = 1, []
        self._cond = threading.Condition()
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                q = parse_qs(urlparse(self.path).query)
                idx = int(q.get("index", ["0"])[0])
                with outer._cond:
                    outer._cond.wait_for(lambda: outer.index > idx,
                                         timeout=5.0)
                    body = ("\n".join(outer.members) + "\n").encode()
                    cur = outer.index
                self.send_response(200)
                self.send_header("X-Fleet-Index", str(cur))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def set_members(self, members):
        with self._cond:
            self.members = list(members)
            self.index += 1
            self._cond.notify_all()


class Rank(Service):
    @raw_method(native="echo")          # answered inside the C++ engine
    def Infer(self, payload, attachment):
        return payload, attachment


def main() -> int:
    rpcz_dir = tempfile.mkdtemp(prefix="fleet-rpcz-")
    assert set_flag("rpcz_dir", rpcz_dir)

    # serving ranks: native engine on the data port (framed protocols
    # only, GIL-free dispatch) + an internal operator port serving the
    # HTTP portal — the production split
    ranks = []
    for _ in range(3):
        o = ServerOptions()
        o.native, o.usercode_inline = True, True
        o.internal_port = 0            # ephemeral operator port
        s = Server(o)
        s.add_service(Rank(), name="M")
        assert s.start("127.0.0.1:0") == 0
        ranks.append(s)
    addrs = [str(s.listen_endpoint) for s in ranks]
    ops = [str(s.internal_endpoint) for s in ranks]
    print(f"ranks: {addrs}")
    print(f"operator ports: {ops}")

    # controller announces the first two ranks
    ctrl = Controller()
    ctrl.set_members(addrs[:2])
    ch = Channel()
    assert ch.init(f"watch://127.0.0.1:{ctrl.port}/members", "rr") == 0
    deadline = time.time() + 10
    while len(ch.load_balancer.servers) < 2:
        assert time.time() < deadline, "watch NS never delivered members"
        time.sleep(0.05)

    # traffic: balanced unary calls over the watch channel, plus a
    # PIPELINED batch on a direct single-rank channel (pipelining rides
    # one exclusive connection, so it is a single-server lane — the
    # balanced channel falls back to per-call RPCs for batches)
    for i in range(4):
        r, _ = ch.call_raw("M.Infer", b"req-%d" % i, timeout_ms=5_000)
        assert bytes(r) == b"req-%d" % i
    direct = Channel()
    assert direct.init(addrs[0]) == 0
    out = direct.call_batch("M.Infer", [b"b%03d" % i for i in range(256)],
                            timeout_ms=10_000)
    assert len(out) == 256 and bytes(out[7]) == b"b007"
    print("traffic flowing: balanced unary + 256-call pipelined batch "
          "(direct rank channel) OK")

    # live membership flip: rank 0 out, rank 2 in — no traffic stops
    ctrl.set_members(addrs[1:])
    deadline = time.time() + 5
    while time.time() < deadline:
        r, _ = ch.call_raw("M.Infer", b"during-flip", timeout_ms=5_000)
        assert bytes(r) == b"during-flip"
        if len(ch.load_balancer.servers) == 2 \
                and str(ch.load_balancer.servers[0].endpoint) != addrs[0]:
            break
        time.sleep(0.02)
    live = [str(n.endpoint) for n in ch.load_balancer.servers]
    assert addrs[0] not in live and addrs[2] in live, live
    print(f"membership flipped under load -> {live}")

    # operator: browse a rank's portal (internal port) through the
    # rpc_view proxy
    proxy = ViewProxy()
    port = proxy.start()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{ops[1]}/status", timeout=5) as r:
        assert r.status == 200
    print(f"rpc_view proxy: http://127.0.0.1:{port}/{ops[1]}/status OK")

    # operator: probe the whole fleet at once (demo caveat: all three
    # "ranks" share THIS process's bvar registry, so the counter is the
    # first rank's — one process per rank in a real fleet)
    res = parallel_fetch(ops, "/vars/rpc_server_m_infer_native_requests")
    for a in ops:
        body = res[a].body.decode().strip() if res[a].ok else "DOWN"
        print(f"  {a} native_requests: {body}")

    # post-mortem: natively-answered calls never enter Python (that is
    # the lane's contract) — send one TRACED call, which always routes
    # through the full dispatch and always records a span, then browse
    # the sqlite mirror that will outlive these ranks
    from brpc_tpu.client import Controller as Cntl
    cntl = Cntl()
    cntl.timeout_ms = 5_000
    cntl.trace_id = 0xF1EE7
    c = ch.call_method("M.Infer", b"traced", cntl=cntl)
    assert not c.failed, c.error_text
    from brpc_tpu.rpcz import browse_persisted, global_span_store
    global_span_store().flush_now()
    spans = browse_persisted(limit=5, trace_id=0xF1EE7)
    print(f"rpcz sqlite mirror ({rpcz_dir}): traced span persisted = "
          f"{[s['method'] for s in spans]}")
    assert spans, "traced span must be browsable post-mortem"

    proxy.stop()
    for s in ranks:
        s.stop()
    set_flag("rpcz_dir", "")
    print("fleet demo OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
